"""paddle.summary / paddle.flops (reference python/paddle/hapi/
model_summary.py, dynamic_flops.py)."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["summary", "flops"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Layer-by-layer output shapes + parameter counts via forward hooks
    (reference model_summary.py:summary)."""
    rows = []
    hooks = []

    def register(layer, name):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            n_params = builtins_sum(
                int(np.prod(p.shape)) for p in l._parameters.values()
                if p is not None)
            rows.append((name or l.__class__.__name__,
                         l.__class__.__name__, shape, n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    import builtins
    builtins_sum = builtins.sum

    for name, sub in net.named_sublayers(include_self=False):
        if not sub._sub_layers:  # leaves only
            register(sub, name)

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) and \
            isinstance(input_size[0], (list, tuple)) else [input_size]
        x = [Tensor(np.zeros([1 if s is None or s == -1 else s
                              for s in size], np.float32))
             for size in sizes]
    was_training = net.training
    net.eval()
    try:
        net(*x)
    finally:
        net.train() if was_training else net.eval()
        for h in hooks:
            h.remove()

    total = builtins_sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = builtins_sum(int(np.prod(p.shape)) for p in net.parameters()
                             if p.trainable)
    width = 72
    print("-" * width)
    print(f"{'Layer (type)':<32}{'Output Shape':<24}{'Param #':<12}")
    print("=" * width)
    for name, cls, shape, n in rows:
        print(f"{name + ' (' + cls + ')':<32}{str(shape):<24}{n:<12}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}


_FLOP_RULES = {}


def _conv_flops(layer, inp, out):
    k = int(np.prod(layer._kernel_size))
    cin = layer._in_channels // layer._groups
    return int(np.prod(out.shape)) * cin * k * 2


def _linear_flops(layer, inp, out):
    return 2 * int(np.prod(inp.shape)) * layer._out_features


def flops(net: Layer, input_size, custom_ops=None, print_detail=False):
    """Forward-pass FLOPs estimate (reference dynamic_flops.py)."""
    total = [0]
    hooks = []

    def register(layer):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
            inp = inputs[0]
            cls = l.__class__.__name__
            if custom_ops and type(l) in custom_ops:
                total[0] += custom_ops[type(l)](l, inp, out)
            elif cls.startswith("Conv"):
                total[0] += _conv_flops(l, inp, out)
            elif cls == "Linear":
                total[0] += _linear_flops(l, inp, out)
        hooks.append(layer.register_forward_post_hook(hook))

    for _, sub in net.named_sublayers():
        if not sub._sub_layers:
            register(sub)
    x = Tensor(np.zeros([1 if s is None or s == -1 else s
                         for s in input_size], np.float32))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        net.train() if was_training else net.eval()
        for h in hooks:
            h.remove()
    return total[0]
