"""hapi: the Keras-like high-level API (reference python/paddle/hapi/)."""
from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401
from .model_summary import summary, flops  # noqa: F401
