"""paddle.metric parity (reference python/paddle/metric/metrics.py:
Metric base + Accuracy/Precision/Recall/Auc; C++ kernels
operators/metrics/{accuracy_op,auc_op}.*)."""
from .metrics import (  # noqa: F401
    Metric, Accuracy, Precision, Recall, Auc, accuracy, mean_iou)
