"""paddle.metric parity (reference python/paddle/metric/metrics.py:
Metric base + Accuracy/Precision/Recall/Auc; C++ kernels
operators/metrics/{accuracy_op,auc_op}.*)."""
from .metrics import Metric, Accuracy, Precision, Recall, Auc, accuracy  # noqa: F401
