"""Metrics (reference python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy",
           "mean_iou"]


def _np(x):
    return np.asarray(x.data) if isinstance(x, Tensor) else np.asarray(x)


def _raw(x):
    """Underlying array WITHOUT forcing a host copy (device arrays stay
    on device; see Accuracy's async path)."""
    return x.data if isinstance(x, Tensor) else x


def _is_device_array(a) -> bool:
    try:
        import jax
        return isinstance(a, jax.Array)
    except Exception:  # pragma: no cover - jax always present here
        return False


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-compute run on device outputs (reference
        Metric.compute); default passthrough."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_raw, label_raw = _raw(pred), _raw(label)
        maxk = max(self.topk)
        if _is_device_array(pred_raw):
            # device path (compiled trainers): the whole top-k check is
            # queued as async device work — no host transfer per step
            import jax.numpy as jnp
            label_j = label_raw if _is_device_array(label_raw) \
                else jnp.asarray(np.asarray(label_raw))
            if label_j.ndim == pred_raw.ndim and label_j.shape[-1] == 1:
                label_j = label_j.squeeze(-1)
            order = jnp.argsort(-pred_raw, axis=-1)[..., :maxk]
            correct = (order == label_j[..., None]).astype(jnp.float32)
            return Tensor(correct)
        pred_np = np.asarray(pred_raw)
        label_np = np.asarray(label_raw)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        order = np.argsort(-pred_np, axis=-1)[..., :maxk]
        correct = order == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _raw(correct)
        if _is_device_array(c):
            # accumulate on device: total becomes a device scalar chain;
            # the blocking read-back happens once, when a logger /
            # evaluate actually wants the number.  The return value
            # keeps the Metric.update contract (the running accuracy)
            # as a lazy float-alike instead of syncing here
            for i, k in enumerate(self.topk):
                self.total[i] = self.total[i] + c[..., :k].sum()
                self.count[i] += int(np.prod(c.shape[:-1]))
            from ..distributed.async_dispatch import LazyValue
            return LazyValue(self.accumulate)
        c = np.asarray(c)
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += int(np.prod(c.shape[:-1]))
        return self.accumulate()

    def accumulate(self):
        res = [float(t) / c if c > 0 else 0.0
               for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Histogram-bucket AUC (reference metrics.py Auc / auc_op.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx[l == 1], 1)
        np.add.at(self._stat_neg, idx[l == 0], 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate from highest threshold down (trapezoid)
        pos = self._stat_pos[::-1]
        neg = self._stat_neg[::-1]
        cum_pos = np.cumsum(pos)
        cum_neg = np.cumsum(neg)
        tpr = cum_pos / tot_pos
        fpr = cum_neg / tot_neg
        trapz = getattr(np, "trapezoid", None) or np.trapz
        return float(trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (reference metrics/accuracy_op.cc)."""
    import jax.numpy as jnp
    from ..core.autograd import apply

    def fn(p, l):
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l2 = l[..., 0]
        else:
            l2 = l
        topk = jnp.argsort(-p, axis=-1)[..., :k]
        hit = (topk == l2[..., None]).any(-1)
        return hit.astype(jnp.float32).mean()

    return apply(fn, input, label, name="accuracy")


def mean_iou(input, label, num_classes):
    """Mean intersection-over-union over a segmentation batch
    (reference mean_iou_op.h). Matches the op's outputs exactly:
    (mean_iou, out_wrong [C], out_correct [C]) where correct[c] counts
    pixels with pred == label == c and a mismatching pixel increments
    wrong[] for BOTH its predicted and true class; per-class
    IoU = correct / (correct + wrong), averaged over classes with a
    nonzero denominator."""
    import numpy as np
    pred = _np(input).astype(np.int64).reshape(-1)
    gt = _np(label).astype(np.int64).reshape(-1)
    correct = np.zeros(num_classes, np.int64)
    wrong = np.zeros(num_classes, np.int64)
    hit = pred == gt

    def in_range(a):
        return (a >= 0) & (a < num_classes)

    # out-of-range ids (ignore_index-style labels) contribute nothing
    np.add.at(correct, pred[hit & in_range(pred)], 1)
    np.add.at(wrong, pred[~hit & in_range(pred)], 1)
    np.add.at(wrong, gt[~hit & in_range(gt)], 1)
    denom = correct + wrong
    valid = denom > 0
    iou = correct / np.maximum(denom, 1)
    miou = float(iou[valid].mean()) if valid.any() else 0.0
    return miou, wrong, correct
