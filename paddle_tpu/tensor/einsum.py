"""Einsum (reference: python/paddle/tensor/einsum.py). Maps directly to
jnp.einsum — XLA fuses it into MXU dot_generals."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import apply
from ..core.tensor import Tensor, to_tensor


def einsum(equation, *operands, name=None):
    ops = [o if isinstance(o, Tensor) else to_tensor(o) for o in operands]
    return apply(lambda *arrs: jnp.einsum(equation, *arrs), *ops, name="einsum")
