"""Tensor creation ops.

Reference parity: python/paddle/tensor/creation.py (fill_constant,
assign, arange, eye, ... backed by C++ ops in
/root/reference/paddle/fluid/operators/fill_constant_op.cc etc.).
Here every creation op lowers to a jnp constructor.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.dtype import convert_dtype, default_float_dtype
from ..core.tensor import Tensor, to_tensor  # noqa: F401  (re-export)


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = default if default is not None else default_float_dtype()
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape.data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.zeros_like(x.data, dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.ones_like(x.data, dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.full_like(x.data, fill_value, dtype=convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        py = (start, end, step)
        d = np.dtype(np.int64) if all(
            isinstance(v, (int, np.integer)) for v in py) else default_float_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def meshgrid(*args, **kwargs):
    arrs = [a.data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    return [Tensor(m) for m in jnp.meshgrid(*arrs, indexing="ij")]


def diag(x, offset=0, padding_value=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x

    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(*out.shape, k=offset, dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)

    return apply(_diag, x, name="diag")


def diagflat(x, offset=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return apply(lambda a: jnp.diagflat(a, k=offset), x, name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x

    def _emb(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return apply(_emb, x, name="diag_embed")


def tril(x, diagonal=0, name=None):
    return apply(lambda a: jnp.tril(a, k=diagonal), x, name="tril")


def triu(x, diagonal=0, name=None):
    return apply(lambda a: jnp.triu(a, k=diagonal), x, name="triu")


def assign(x, output=None):
    """paddle.assign parity (operators/assign_op.cc)."""
    src = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    if output is not None:
        output._data = src
        return output
    return Tensor(src)


def clone(x, name=None):
    return x.clone()


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def complex(real, imag, name=None):
    return apply(lambda r, i: jax.lax.complex(r, i), real, imag, name="complex")


import jax  # noqa: E402  (used by complex)
