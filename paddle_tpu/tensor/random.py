"""Random sampling ops.

Reference parity: python/paddle/tensor/random.py (uniform_random_op.cc,
gaussian_random_op.cc, randint_op.cc, randperm_op.cc, bernoulli_op.cc,
multinomial_op.cc). The reference uses stateful per-device cuRAND; here
keys come from core.random (global generator in eager mode, explicit key
stack under tracing — see core/random.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, default_float_dtype
from ..core.random import next_key
from ..core.tensor import Tensor, to_tensor


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape.data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _fdt(dtype):
    d = convert_dtype(dtype)
    return d if d is not None else default_float_dtype()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _fdt(dtype),
                                     minval=float(min), maxval=float(max)))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(
        jax.random.key(seed) if seed else next_key(),
        x.data.shape, x.data.dtype, minval=float(min), maxval=float(max))
    return x


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), _shape(shape), _fdt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean.data if isinstance(mean, Tensor) else mean
        s = std.data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(m + s * jax.random.normal(next_key(), out_shape,
                                                default_float_dtype()))
    return Tensor(mean + std * jax.random.normal(next_key(), _shape(shape),
                                                 default_float_dtype()))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (mean + std * jax.random.normal(next_key(), x.data.shape,
                                              x.data.dtype))
    return x


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    return Tensor(mean + std * jax.random.normal(next_key(), _shape(shape),
                                                 _fdt(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), int(low),
                                     int(high), convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype) if dtype is not None else x.dtype
    return Tensor(jax.random.randint(next_key(), tuple(x.shape), int(low),
                                     int(high)).astype(d))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(
        convert_dtype(dtype)))


def shuffle(x, axis=0, name=None):
    return Tensor(jax.random.permutation(next_key(), x.data, axis=axis,
                                         independent=False))


def bernoulli(x, name=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jax.random.bernoulli(next_key(), x.data).astype(x.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(next_key(), p, x.data.shape).astype(x.dtype)
    return x


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    if x.data.ndim == 1:
        out = jax.random.choice(next_key(), x.data.shape[0], (num_samples,),
                                replace=replacement, p=x.data / x.data.sum())
        return Tensor(out.astype(jnp.int64))
    n = x.data.shape[1]
    keys = jax.random.split(next_key(), x.data.shape[0])
    sample_row = jax.vmap(
        lambda k, p: jax.random.choice(k, n, (num_samples,),
                                       replace=replacement, p=p / p.sum()))
    return Tensor(sample_row(keys, x.data).astype(jnp.int64))


def poisson(x, name=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jax.random.poisson(next_key(), x.data).astype(x.dtype))


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(next_key(), x.data.shape, x.data.dtype)
               / lam)
    return x


def binomial(count, prob, name=None):
    c = count.data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob.data if isinstance(prob, Tensor) else jnp.asarray(prob)
    return Tensor(jax.random.binomial(next_key(), c.astype(jnp.float32),
                                      p).astype(jnp.int64))
