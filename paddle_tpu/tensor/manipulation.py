"""Shape / layout manipulation ops.

Reference parity: python/paddle/tensor/manipulation.py and C++ kernels
(reshape_op.cc, transpose_op.cc, concat_op.cc, split_op.cc, gather_op.cc,
scatter_op.cc, ...). All static-shape — XLA requires it, and that is also
what makes these free (reshape/transpose usually fuse away entirely).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.dtype import convert_dtype
from ..core.errors import InvalidArgumentError
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _ints(seq):
    if isinstance(seq, Tensor):
        return tuple(int(v) for v in np.asarray(seq.data))
    if isinstance(seq, (int, np.integer)):
        return (int(seq),)
    return tuple(int(v.item()) if isinstance(v, Tensor) else int(v) for v in seq)


def reshape(x, shape, name=None):
    s = _ints(shape)
    return apply(lambda a: jnp.reshape(a, s), x, name="reshape")


def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x.data, _ints(shape))
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _flat(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply(_flat, x, name="flatten")


def transpose(x, perm, name=None):
    p = _ints(perm)
    return apply(lambda a: jnp.transpose(a, p), x, name="transpose")


def t(x, name=None):
    return apply(lambda a: a.T, x, name="t")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), x, name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), x, name="swapaxes")


def concat(x, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    xs = [_t(v) for v in x]
    return apply(lambda *arrs: jnp.concatenate(arrs, axis=ax), *xs, name="concat")


def stack(x, axis=0, name=None):
    xs = [_t(v) for v in x]
    return apply(lambda *arrs: jnp.stack(arrs, axis=axis), *xs, name="stack")


def unstack(x, axis=0, num=None, name=None):
    x = _t(x)
    n = num if num is not None else x.shape[axis]
    outs = apply(
        lambda a: tuple(jnp.squeeze(s, axis=axis)
                        for s in jnp.split(a, n, axis=axis)),
        x, name="unstack")
    return list(outs)


def unbind(x, axis=0):
    return unstack(x, axis=axis)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise InvalidArgumentError(
                f"split: dim {dim} not divisible by {num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = list(_ints(num_or_sections))
        total = 0
        unk = -1
        for i, s in enumerate(sizes):
            if s < 0:
                unk = i
            else:
                total += s
        if unk >= 0:
            sizes[unk] = dim - total
    offsets = np.cumsum([0] + sizes[:-1])
    outs = apply(
        lambda a: tuple(jax.lax.slice_in_dim(a, int(o), int(o) + int(s), axis=ax)
                        for o, s in zip(offsets, sizes)),
        x, name="split")
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def squeeze(x, axis=None, name=None):
    def _sq(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = _ints(axis)
        axes = tuple(ax % a.ndim for ax in axes)
        keep = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=keep) if keep else a
    return apply(_sq, x, name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = _ints(axis)
    def _unsq(a):
        out = a
        for ax in sorted(axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply(_unsq, x, name="unsqueeze")


def flip(x, axis, name=None):
    axes = _ints(axis)
    return apply(lambda a: jnp.flip(a, axis=axes), x, name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, name="rot90")


def roll(x, shifts, axis=None, name=None):
    sh = _ints(shifts) if not isinstance(shifts, int) else shifts
    ax = _ints(axis) if axis is not None and not isinstance(axis, int) else axis
    return apply(lambda a: jnp.roll(a, sh, axis=ax), x, name="roll")


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), x, name="tile")


def expand(x, shape, name=None):
    s = _ints(shape)

    def _expand(a):
        target = list(s)
        # -1 means keep original dim (paddle semantics)
        offset = len(target) - a.ndim
        for i in range(len(target)):
            if target[i] == -1:
                target[i] = a.shape[i - offset]
        return jnp.broadcast_to(a, tuple(target))

    return apply(_expand, x, name="expand")


def expand_as(x, y, name=None):
    target = tuple(_t(y).data.shape)
    return apply(lambda a: jnp.broadcast_to(a, target), x, name="expand_as")


def broadcast_to(x, shape, name=None):
    s = _ints(shape)
    return apply(lambda a: jnp.broadcast_to(a, s), x, name="broadcast_to")


def broadcast_tensors(inputs, name=None):
    xs = [_t(v) for v in inputs]
    outs = apply(lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)), *xs,
                 name="broadcast_tensors")
    return list(outs)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def cast(x, dtype):
    d = convert_dtype(dtype)
    return apply(lambda a: a.astype(d), x, name="cast")


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=ax),
                 x, _t(index), name="gather")


def gather_nd(x, index, name=None):
    def _gnd(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        flat = tuple(idx[..., i] for i in range(k))
        return a[flat]
    return apply(_gnd, x, _t(index), name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def _sc(a, idx, upd):
        idx = idx.astype(jnp.int32)
        if overwrite:
            return a.at[idx].set(upd)
        # paddle overwrite=False: zero the rows then accumulate
        zeroed = a.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)
    return apply(_sc, x, _t(index), _t(updates), name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._data = out.data
    return x


def scatter_nd(index, updates, shape, name=None):
    s = _ints(shape)

    def _snd(idx, upd):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        out = jnp.zeros(s, upd.dtype)
        flat = tuple(idx[..., i] for i in range(k))
        return out.at[flat].add(upd)

    return apply(_snd, _t(index), _t(updates), name="scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    def _snda(a, idx, upd):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        flat = tuple(idx[..., i] for i in range(k))
        return a.at[flat].add(upd)
    return apply(_snda, x, _t(index), _t(updates), name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis=axis)


def index_sample(x, index):
    def _is(a, idx):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, idx.astype(jnp.int32)]
    return apply(_is, x, _t(index), name="index_sample")


def index_add(x, index, axis, value, name=None):
    def _ia(a, idx, v):
        idx = idx.astype(jnp.int32)
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vmoved)
        return jnp.moveaxis(out, 0, axis)
    return apply(_ia, x, _t(index), _t(value), name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(_t(i).data.astype(jnp.int32) for i in indices)

    def _ip(a, v):
        return a.at[idx].add(v) if accumulate else a.at[idx].set(v)

    return apply(_ip, x, _t(value), name="index_put")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
                 arr, _t(indices), name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def _pa(a, idx, v):
        idx = idx.astype(jnp.int32)
        v = jnp.broadcast_to(v, idx.shape)
        dims = [jnp.arange(s).reshape([-1 if d == i else 1 for d in range(a.ndim)])
                for i, s in enumerate(idx.shape)]
        full_idx = tuple(idx if i == axis else jnp.broadcast_to(dims[i], idx.shape)
                         for i in range(a.ndim))
        if reduce == "assign":
            return a.at[full_idx].set(v)
        if reduce == "add":
            return a.at[full_idx].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[full_idx].multiply(v)
        raise InvalidArgumentError(f"unknown reduce {reduce}")
    return apply(_pa, arr, _t(indices), _t(values), name="put_along_axis")


def slice(input, axes, starts, ends, name=None):
    """operators/slice_op.cc parity."""
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)

    def _slice(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            dim = out.shape[ax]
            # clamp into [0, dim] like the reference slice op
            s2 = max(0, min(s + dim if s < 0 else s, dim))
            e2 = max(s2, min(e + dim if e < 0 else e, dim))
            out = jax.lax.slice_in_dim(out, s2, e2, axis=ax)
        return out

    return apply(_slice, input, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)
    strides_ = _ints(strides)

    import builtins

    def _ss(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides_):
            sl[ax] = builtins.slice(s, e, st)
        return a[tuple(sl)]

    return apply(_ss, x, name="strided_slice")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """operators/shard_index_op.cc parity — used by parallel embedding
    (reference collective.py:527 _parallel_embedding)."""
    size = (index_num + nshards - 1) // nshards

    def _shard(idx):
        in_shard = (idx // size) == shard_id
        return jnp.where(in_shard, idx % size, ignore_value)

    return apply(_shard, input, name="shard_index")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = _t(x)
    res = jnp.unique(x.data, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    x = _t(x)
    a = np.asarray(x.data)
    if axis is None:
        a = a.reshape(-1)
    keep = np.ones(a.shape[0], dtype=bool)
    keep[1:] = np.any(a[1:] != a[:-1], axis=tuple(range(1, a.ndim))) if a.ndim > 1 \
        else a[1:] != a[:-1]
    out = [Tensor(jnp.asarray(a[keep]))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, a.shape[0]))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


def masked_select(x, mask, name=None):
    """Output shape is data-dependent, so indices are computed on host; the
    gather itself is tape-recorded so gradients flow back into x."""
    x, mask = _t(x), _t(mask)
    idx = np.nonzero(np.asarray(mask.data).reshape(-1))[0]
    return apply(lambda a: a.reshape(-1)[jnp.asarray(idx)], x,
                 name="masked_select")


def masked_fill(x, mask, value, name=None):
    v = value.data if isinstance(value, Tensor) else value
    return apply(lambda a, m: jnp.where(m, v, a), x, _t(mask), name="masked_fill")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """pad_op.cc / pad3d_op.cc parity. `pad` is either 2*ndim ints covering
    every dim (np.pad order) or 2*k ints covering the spatial dims of
    `data_format` (paddle convention: last-dim pairs first)."""
    x = _t(x)
    nd = x.data.ndim
    p = _ints(pad)
    if len(p) == 2 * nd:
        widths = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
    else:
        k = len(p) // 2
        widths = [(0, 0)] * nd
        if data_format.endswith("C") and data_format.startswith("N"):  # NHWC/NDHWC/NLC
            spatial = list(range(1, 1 + k))
        else:  # NCHW/NCDHW/NCL
            spatial = list(range(nd - k, nd))
        # paddle lists pads innermost-dim first
        for i, ax in enumerate(reversed(spatial)):
            widths[ax] = (p[2 * i], p[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return apply(lambda a: jnp.pad(a, widths, mode="constant",
                                       constant_values=value), x, name="pad")
    return apply(lambda a: jnp.pad(a, widths, mode=jmode), x, name="pad")


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats.data if isinstance(repeats, Tensor) else repeats
    return apply(lambda a: jnp.repeat(a, r, axis=axis), x, name="repeat_interleave")


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = np.asarray(ax.data).tolist()
    return apply(lambda a, b: jnp.tensordot(a, b, axes=ax), x, _t(y),
                 name="tensordot")


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
                 name="as_complex")


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x,
                 name="as_real")


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, _t(x), name="atleast_1d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, _t(x), name="atleast_2d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, _t(x), name="atleast_3d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def crop(x, shape=None, offsets=None, name=None):
    """Crop a static window (reference crop_tensor_op): take
    x[offsets[i] : offsets[i] + shape[i]] along every dim. shape entries
    of -1 keep everything from the offset on."""
    xa = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    nd = xa.ndim
    offs = [int(o) for o in (offsets if offsets is not None
                             else [0] * nd)]
    shp = [int(s) for s in (shape if shape is not None
                            else list(xa.shape))]
    if len(offs) != nd or len(shp) != nd:
        raise ValueError(f"crop: offsets/shape must have {nd} entries")
    sizes = [xa.shape[i] - offs[i] if shp[i] == -1 else shp[i]
             for i in range(nd)]
    for i in range(nd):
        if offs[i] + sizes[i] > xa.shape[i]:
            raise ValueError(
                f"crop window exceeds dim {i}: {offs[i]}+{sizes[i]} > "
                f"{xa.shape[i]}")

    def fn(a):
        return jax.lax.slice(a, offs,
                             [o + s for o, s in zip(offs, sizes)])

    return apply(fn, x, name="crop")


def squeeze_(x, axis=None, name=None):
    """In-place squeeze (reference squeeze_ / Squeeze2 inplace kernel)."""
    from ..nn.functional.activation import _inplace
    return _inplace(x, lambda a: squeeze(a, axis=axis))


def unsqueeze_(x, axis, name=None):
    """In-place unsqueeze (reference unsqueeze_)."""
    from ..nn.functional.activation import _inplace
    return _inplace(x, lambda a: unsqueeze(a, axis))


# reference paddle 2.0 exports the op under both names
# (crop_tensor_op.cc; python crop_tensor / crop)
crop_tensor = crop
