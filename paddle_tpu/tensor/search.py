"""Search / sort / selection ops.

Reference parity: python/paddle/tensor/search.py (arg_min_max_op,
top_k_v2_op.cc, argsort_op.cc, where_op.cc, masked_select_op.cc, ...).
top_k uses jax.lax.top_k which XLA lowers to a TPU-efficient partial sort.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = convert_dtype(dtype)
    return apply(lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim).astype(d),
                 x, name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = convert_dtype(dtype)
    return apply(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim).astype(d),
                 x, name="argmin")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def _as(a):
        if descending and stable:
            # stable descending: flipping a stable ascending sort reverses
            # tie order; sort the flipped array instead and remap indices
            # (exact for every dtype, unlike negating the keys).
            n = a.shape[axis]
            idx_rev = jnp.argsort(jnp.flip(a, axis=axis), axis=axis,
                                  stable=True)
            idx = n - 1 - jnp.flip(idx_rev, axis=axis)
        else:
            idx = jnp.argsort(a, axis=axis, stable=stable or descending)
            if descending:
                idx = jnp.flip(idx, axis=axis)
        return idx.astype(jnp.int64)
    return apply(_as, x, name="argsort")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def _sort(a):
        out = jnp.sort(a, axis=axis, stable=stable)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out
    return apply(_sort, x, name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)

    def _topk(a):
        ax = a.ndim - 1 if axis is None else axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, kk)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int64), -1, ax))

    vals, idx = apply(_topk, x, name="top_k")
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _kth(a):
        ax = axis % a.ndim
        srt = jnp.sort(a, axis=ax)
        idx = jnp.argsort(a, axis=ax)
        v = jnp.take(srt, k - 1, axis=ax)
        i = jnp.take(idx, k - 1, axis=ax).astype(jnp.int64)
        if keepdim:
            v = jnp.expand_dims(v, ax)
            i = jnp.expand_dims(i, ax)
        return v, i
    return apply(_kth, x, name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    x = _t(x)
    a = np.asarray(x.data)
    ax = axis % a.ndim
    from scipy import stats as _stats  # scipy ships with the jax dep tree
    vals = _stats.mode(a, axis=ax, keepdims=True).mode
    # paddle returns the LAST index equal to the mode along axis
    eq = a == vals
    n = a.shape[ax]
    pos = np.arange(n).reshape([-1 if d == ax else 1 for d in range(a.ndim)])
    idx = np.max(np.where(eq, pos, -1), axis=ax, keepdims=True)
    if not keepdim:
        vals = np.squeeze(vals, axis=ax)
        idx = np.squeeze(idx, axis=ax)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idx, dtype=jnp.int64))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), _t(condition), x, y,
                 name="where")


def nonzero(x, as_tuple=False):
    x = _t(x)
    idx = np.nonzero(np.asarray(x.data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i, dtype=jnp.int64)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1), dtype=jnp.int64))


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms
    return _ms(x, mask)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else jnp.int64
    return apply(lambda s, v: jnp.searchsorted(s, v, side=side).astype(d),
                 _t(sorted_sequence), _t(values), name="searchsorted")


def index_select(x, index, axis=0, name=None):
    from .manipulation import index_select as _is
    return _is(x, index, axis)


def index_sample(x, index):
    from .manipulation import index_sample as _is
    return _is(x, index)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)
