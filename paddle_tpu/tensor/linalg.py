"""Linear algebra ops.

Reference parity: python/paddle/tensor/linalg.py (norm_op.cc, p_norm_op.cc,
cholesky_op.cc, svd, qr, matrix_power, ...). Decompositions lower to
XLA's native linalg (QR/SVD/Cholesky run on TPU via XLA custom calls).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.tensor import Tensor, to_tensor
from .math import matmul, bmm, dot, mv  # noqa: F401  (re-export)


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    """paddle.linalg.norm: frobenius by default; p in {1,2,inf,-inf,'fro','nuc'} or float."""
    def _norm(a):
        pp = p
        if pp is None:
            pp = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2
        if pp == "fro":
            ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if pp == "nuc":
            s = jnp.linalg.svd(a, compute_uv=False)
            return jnp.sum(s, axis=-1, keepdims=keepdim)
        if pp in (np.inf, float("inf")):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if pp in (-np.inf, float("-inf")):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if pp == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.sum(jnp.abs(a) ** pp, axis=ax, keepdims=keepdim) ** (1.0 / pp)

    return apply(_norm, x, name="norm")


def p_norm(x, p=2, axis=None, keepdim=False):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def vector_norm(x, p=2, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return norm(x, p=p, axis=list(axis), keepdim=keepdim)


def cond(x, p=None, name=None):
    x = _t(x)
    return Tensor(jnp.asarray(np.linalg.cond(np.asarray(x.data),
                                             p if p is not None else 2)))


def cholesky(x, upper=False, name=None):
    def _chol(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply(_chol, x, name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def _cs(b, L):
        Lm = jnp.swapaxes(L, -1, -2) if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(Lm, -1, -2), z,
                                                 lower=False)
    return apply(_cs, x, y, name="cholesky_solve")


def inv(x, name=None):
    return apply(jnp.linalg.inv, x, name="inverse")


inverse = inv


def det(x, name=None):
    return apply(jnp.linalg.det, x, name="determinant")


def slogdet(x, name=None):
    def _sld(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return apply(_sld, x, name="slogdet")


def svd(x, full_matrices=False, name=None):
    return apply(lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
                 x, name="svd")


def svdvals(x, name=None):
    return apply(lambda a: jnp.linalg.svd(a, compute_uv=False), x, name="svdvals")


def qr(x, mode="reduced", name=None):
    return apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x, name="qr")


def lu(x, pivot=True, get_infos=False, name=None):
    x = _t(x)
    import scipy.linalg as sla
    a = np.asarray(x.data)
    lu_, piv = sla.lu_factor(a)
    outs = (Tensor(jnp.asarray(lu_)), Tensor(jnp.asarray(piv.astype(np.int32) + 1)))
    if get_infos:
        return outs + (Tensor(jnp.zeros((), jnp.int32)),)
    return outs


def eig(x, name=None):
    x = _t(x)
    w, v = np.linalg.eig(np.asarray(x.data))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply(lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=True)),
                 x, name="eigh")


def eigvals(x, name=None):
    x = _t(x)
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(x.data))))


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a), x, name="eigvalsh")


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), x, name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = _t(x)
    return Tensor(jnp.linalg.matrix_rank(x.data, rtol=tol))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                 x, name="pinv")


def solve(x, y, name=None):
    return apply(lambda a, b: jnp.linalg.solve(a, b), x, y, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def _ts(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply(_ts, x, y, name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = _t(x), _t(y)
    sol, res, rank, sv = np.linalg.lstsq(np.asarray(x.data), np.asarray(y.data),
                                         rcond=rcond)
    return (Tensor(jnp.asarray(sol)), Tensor(jnp.asarray(res)),
            Tensor(jnp.asarray(rank)), Tensor(jnp.asarray(sv)))


def cross(x, y, axis=9, name=None):
    def _cross(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply(_cross, x, y, name="cross")


def multi_dot(x, name=None):
    xs = [_t(v) for v in x]
    return apply(lambda *arrs: jnp.linalg.multi_dot(list(arrs)), *xs,
                 name="multi_dot")


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = fweights.data if isinstance(fweights, Tensor) else fweights
    aw = aweights.data if isinstance(aweights, Tensor) else aweights
    return apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), x, name="cov")


def householder_product(x, tau, name=None):
    def _hp2d(a, t):
        m, n = a.shape
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(t.shape[0]):
            ar = jnp.arange(m)
            v = jnp.where(ar > i, a[:, i], jnp.where(ar == i, 1.0, 0.0))
            H = jnp.eye(m, dtype=a.dtype) - t[i] * jnp.outer(v, v)
            q = q @ H
        return q[:, :n]

    def _hp(a, t):
        if a.ndim == 2:
            return _hp2d(a, t)
        batch = a.shape[:-2]
        af = a.reshape((-1,) + a.shape[-2:])
        tf = t.reshape((-1, t.shape[-1]))
        out = jax.vmap(_hp2d)(af, tf)
        return out.reshape(batch + out.shape[-2:])

    return apply(_hp, x, tau, name="householder_product")


def dist(x, y, p=2, name=None):
    """p-norm of (x - y) with broadcasting (reference dist_op.cc).
    p=0 counts non-zero entries; p=inf/-inf are max/min |diff|."""
    pf = float(p)

    def fn(a, b):
        d = (a - b).astype(jnp.float32)
        if pf == 0:
            return jnp.sum((d != 0).astype(jnp.float32))
        if jnp.isinf(pf):
            m = jnp.abs(d)
            return jnp.max(m) if pf > 0 else jnp.min(m)
        return jnp.sum(jnp.abs(d) ** pf) ** (1.0 / pf)

    return apply(fn, x, y, name="dist")
