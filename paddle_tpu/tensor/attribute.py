"""Tensor attribute ops (reference: python/paddle/tensor/attribute.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.tensor import Tensor, to_tensor


def shape(input):
    """paddle.shape returns a 1-D int tensor (shape_op.cc)."""
    x = input if isinstance(input, Tensor) else to_tensor(input)
    return Tensor(jnp.asarray(x.data.shape, dtype=jnp.int32))


def rank(input):
    x = input if isinstance(input, Tensor) else to_tensor(input)
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def is_floating_point(x):
    return np.issubdtype(np.dtype(x.dtype), np.floating)


def is_integer(x):
    return np.issubdtype(np.dtype(x.dtype), np.integer)


def is_complex(x):
    return np.issubdtype(np.dtype(x.dtype), np.complexfloating)


def real(x, name=None):
    return apply(jnp.real, x, name="real")


def imag(x, name=None):
    return apply(jnp.imag, x, name="imag")


def conj(x, name=None):
    return apply(jnp.conj, x, name="conj")


def angle(x, name=None):
    return apply(jnp.angle, x, name="angle")
