"""Tensor attribute ops (reference: python/paddle/tensor/attribute.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.tensor import Tensor, to_tensor


def shape(input):
    """paddle.shape returns a 1-D int tensor (shape_op.cc)."""
    x = input if isinstance(input, Tensor) else to_tensor(input)
    return Tensor(jnp.asarray(x.data.shape, dtype=jnp.int32))


def rank(input):
    x = input if isinstance(input, Tensor) else to_tensor(input)
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def is_floating_point(x):
    return np.issubdtype(np.dtype(x.dtype), np.floating)


def is_integer(x):
    return np.issubdtype(np.dtype(x.dtype), np.integer)


def is_complex(x):
    return np.issubdtype(np.dtype(x.dtype), np.complexfloating)


def real(x, name=None):
    return apply(jnp.real, x, name="real")


def imag(x, name=None):
    return apply(jnp.imag, x, name="imag")


def conj(x, name=None):
    return apply(jnp.conj, x, name="conj")


def angle(x, name=None):
    return apply(jnp.angle, x, name="angle")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Configure Tensor repr formatting (reference
    python/paddle/tensor/to_string.py set_printoptions). Tensor.__repr__
    prints through numpy, so this maps onto numpy's print options."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    np.set_printoptions(**kw)
