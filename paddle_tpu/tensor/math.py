"""Elementwise / reduction / matrix math ops.

Reference parity: python/paddle/tensor/math.py and the C++ kernels under
/root/reference/paddle/fluid/operators/ (activation_op.cc, elementwise/,
reduce_ops/, matmul_v2_op.cc, cumsum_op.cc, ...). Every op is a jnp/lax
lowering; gradients come from jax.vjp via the eager tape — there are no
hand-written grad kernels to keep in sync (the reference maintains a grad
op per forward op via GradOpMaker).

Broadcasting follows numpy rules, which is what the reference's
elementwise ops implement with their `axis` attribute; the legacy `axis`
argument is accepted for the common cases.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = np.asarray(axis.data)
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(v) for v in axis)
    return int(axis)


# --------------------------------------------------------------------------
# binary elementwise
# --------------------------------------------------------------------------

def _binary(fname, jfn):
    def op(x, y, name=None):
        return apply(jfn, x, y, name=fname)
    op.__name__ = fname
    return op


add = _binary("add", lambda a, b: jnp.add(a, b))
subtract = _binary("subtract", lambda a, b: jnp.subtract(a, b))
multiply = _binary("multiply", lambda a, b: jnp.multiply(a, b))
divide = _binary("divide", lambda a, b: jnp.true_divide(a, b))
floor_divide = _binary("floor_divide", lambda a, b: jnp.floor_divide(a, b))
remainder = _binary("remainder", lambda a, b: jnp.remainder(a, b))
mod = remainder
floor_mod = remainder
pow = _binary("pow", lambda a, b: jnp.power(a, b))
maximum = _binary("maximum", lambda a, b: jnp.maximum(a, b))
minimum = _binary("minimum", lambda a, b: jnp.minimum(a, b))
fmax = _binary("fmax", lambda a, b: jnp.fmax(a, b))
fmin = _binary("fmin", lambda a, b: jnp.fmin(a, b))
atan2 = _binary("atan2", lambda a, b: jnp.arctan2(a, b))
heaviside = _binary("heaviside", lambda a, b: jnp.heaviside(a, b))
hypot = _binary("hypot", lambda a, b: jnp.hypot(a, b))
logaddexp = _binary("logaddexp", lambda a, b: jnp.logaddexp(a, b))
nextafter = _binary("nextafter", lambda a, b: jnp.nextafter(a, b))
copysign = _binary("copysign", lambda a, b: jnp.copysign(a, b))
gcd = _binary("gcd", lambda a, b: jnp.gcd(a, b))
lcm = _binary("lcm", lambda a, b: jnp.lcm(a, b))


def elementwise_add(x, y, axis=-1, name=None):
    return add(x, y)


def elementwise_sub(x, y, axis=-1, name=None):
    return subtract(x, y)


def elementwise_mul(x, y, axis=-1, name=None):
    return multiply(x, y)


def elementwise_div(x, y, axis=-1, name=None):
    return divide(x, y)


# --------------------------------------------------------------------------
# unary elementwise
# --------------------------------------------------------------------------

def _unary(fname, jfn):
    def op(x, name=None):
        return apply(jfn, x, name=fname)
    op.__name__ = fname
    return op


sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
ceil = _unary("ceil", jnp.ceil)
floor = _unary("floor", jnp.floor)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda a: a - jnp.trunc(a))
sign = _unary("sign", jnp.sign)
neg = _unary("neg", jnp.negative)
reciprocal = _unary("reciprocal", lambda a: 1.0 / a)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
i0 = _unary("i0", lambda a: jax.scipy.special.i0(a))
i1 = _unary("i1", lambda a: jax.scipy.special.i1(a))
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)
logit = _unary("logit", lambda a: jnp.log(a / (1 - a)))
sigmoid = _unary("sigmoid", jax.nn.sigmoid)


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply(lambda a: jnp.clip(a, lo, hi), x, name="clip")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                          neginf=neginf), x, name="nan_to_num")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """operators/scale_op.cc parity."""
    s = scale.item() if isinstance(scale, Tensor) else scale

    def _scale(a):
        out = a * s + bias if bias_after_scale else (a + bias) * s
        return out

    out = apply(_scale, x, name="scale")
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), x, name="stanh")


def multiplex(inputs, index, name=None):
    def _mux(idx, *arrs):
        stacked = jnp.stack(arrs, axis=0)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]
    return apply(_mux, index, *inputs, name="multiplex")


# --------------------------------------------------------------------------
# reductions
# --------------------------------------------------------------------------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = convert_dtype(dtype)
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.sum(a, axis=ax, dtype=d, keepdims=keepdim),
                 x, name="reduce_sum")


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim),
                 x, name="reduce_mean")


def max(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.max(a, axis=ax, keepdims=keepdim),
                 x, name="reduce_max")


def min(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.min(a, axis=ax, keepdims=keepdim),
                 x, name="reduce_min")


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = convert_dtype(dtype)
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.prod(a, axis=ax, dtype=d, keepdims=keepdim),
                 x, name="reduce_prod")


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jax.scipy.special.logsumexp(a, axis=ax,
                                                       keepdims=keepdim),
                 x, name="logsumexp")


def all(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x, name="reduce_all")


def any(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x, name="reduce_any")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim),
                 x, name="count_nonzero")


def cumsum(x, axis=None, dtype=None, name=None):
    d = convert_dtype(dtype)

    def _cumsum(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)

    return apply(_cumsum, x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    d = convert_dtype(dtype)

    def _cumprod(a):
        if dim is None:
            return jnp.cumprod(a.reshape(-1), dtype=d)
        return jnp.cumprod(a, axis=int(dim), dtype=d)

    return apply(_cumprod, x, name="cumprod")


def _cum_extremum(x, axis, dtype, largest, opname):
    """Returns (values, indices) like paddle.cummax/cummin — the running
    extremum and the index where it was attained, via an associative scan
    over (value, index) pairs."""
    idx_dt = convert_dtype(dtype)

    def _cm(a):
        flat = axis is None
        arr = a.reshape(-1) if flat else a
        ax = 0 if flat else int(axis) % arr.ndim
        pos = jnp.arange(arr.shape[ax]).reshape(
            [-1 if d == ax else 1 for d in range(arr.ndim)])
        pos = jnp.broadcast_to(pos, arr.shape)

        def combine(l, r):
            lv, li = l
            rv, ri = r
            take_r = rv >= lv if largest else rv <= lv
            return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

        vals, idx = jax.lax.associative_scan(combine, (arr, pos), axis=ax)
        return vals, idx.astype(idx_dt)

    return apply(_cm, x, name=opname)


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extremum(x, axis, dtype, True, "cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extremum(x, axis, dtype, False, "cummin")


def add_n(inputs, name=None):
    """operators/sum_op.cc parity."""
    if isinstance(inputs, Tensor):
        return inputs
    return apply(lambda *arrs: jax.tree_util.tree_reduce(jnp.add, list(arrs)),
                 *inputs, name="add_n")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend.data if isinstance(prepend, Tensor) else prepend
    app = append.data if isinstance(append, Tensor) else append
    return apply(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
                 x, name="diff")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                 x, name="trace")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), x, y, weight, name="lerp")
    return apply(lambda a, b: a + weight * (b - a), x, y, name="lerp")


def kron(x, y, name=None):
    return apply(lambda a, b: jnp.kron(a, b), x, y, name="kron")


def inner(x, y, name=None):
    return apply(lambda a, b: jnp.inner(a, b), x, y, name="inner")


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), x, y, name="outer")


# --------------------------------------------------------------------------
# matrix math — these land on the MXU; keep operands large + bf16-friendly
# --------------------------------------------------------------------------

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """matmul_v2_op.cc parity. XLA maps this to MXU dot_general; the
    transpose flags become dot dimension numbers rather than materialized
    transposes."""

    def _mm(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return apply(_mm, x, y, name="matmul")


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply(lambda a, b: jnp.einsum("bij,bjk->bik", a, b), x, y, name="bmm")


def dot(x, y, name=None):
    def _dot(a, b):
        if a.ndim == 2:
            return jnp.sum(a * b, axis=-1)
        return jnp.dot(a, b)
    return apply(_dot, x, y, name="dot")


def mv(x, y, name=None):
    return apply(lambda a, b: jnp.matmul(a, b), x, y, name="mv")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                 input, x, y, name="addmm")


# --------------------------------------------------------------------------
# stats
# --------------------------------------------------------------------------

def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x, name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x, name="var")


def median(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x, name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim),
                 x, name="nanmedian")


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim),
                 x, name="nanmean")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    d = convert_dtype(dtype)
    return apply(lambda a: jnp.nansum(a, axis=ax, dtype=d, keepdims=keepdim),
                 x, name="nansum")


def quantile(x, q, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    qv = q.data if isinstance(q, Tensor) else q
    return apply(lambda a: jnp.quantile(a, jnp.asarray(qv), axis=ax,
                                        keepdims=keepdim), x, name="quantile")


def histogram(x, bins=100, min=0, max=0, name=None):
    x = _t(x)
    a = x.data
    lo, hi = (min, max) if (min != 0 or max != 0) else (float(jnp.min(a)), float(jnp.max(a)))
    hist, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
    return Tensor(hist)


def bincount(x, weights=None, minlength=0, name=None):
    x = _t(x)
    w = weights.data if isinstance(weights, Tensor) else weights
    return Tensor(jnp.bincount(x.data, weights=w, minlength=minlength))


def clip_by_norm(x, max_norm, name=None):
    """Scale x down so its L2 norm is at most max_norm (reference
    clip_by_norm_op.h)."""
    def fn(a):
        norm = jnp.sqrt(jnp.maximum(jnp.sum(a.astype(jnp.float32) ** 2),
                                    1e-12))
        scale = jnp.minimum(max_norm / norm, 1.0).astype(a.dtype)
        return a * scale

    return apply(fn, x, name="clip_by_norm")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Numerically-stable cumulative logsumexp (reference
    logcumsumexp_op): running max + rescaled running sum along `axis`
    (flattened when axis is None), as one lax.scan."""
    def fn(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis % arr.ndim
        m = jnp.moveaxis(arr.astype(jnp.float32), ax, 0)

        def step(carry, v):
            run_max, run_sum = carry
            new_max = jnp.maximum(run_max, v)
            run_sum = run_sum * jnp.exp(run_max - new_max) + \
                jnp.exp(v - new_max)
            return (new_max, run_sum), new_max + jnp.log(run_sum)

        init = (jnp.full(m.shape[1:], -jnp.inf, jnp.float32),
                jnp.zeros(m.shape[1:], jnp.float32))
        _, out = jax.lax.scan(step, init, m)
        out = jnp.moveaxis(out, 0, ax)
        return out.astype(dtype or a.dtype) if jnp.issubdtype(
            a.dtype, jnp.floating) else out

    return apply(fn, x, name="logcumsumexp")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal integration (reference trapezoid op /
    paddle.trapezoid)."""
    if x is not None:
        def fn(ya, xa):
            return jnp.trapezoid(ya, xa, axis=axis)
        return apply(fn, y, x, name="trapezoid")

    step = 1.0 if dx is None else float(dx)

    def fn(ya):
        return jnp.trapezoid(ya, dx=step, axis=axis)
    return apply(fn, y, name="trapezoid")


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clamp along `axis` (reference renorm_op): every
    slice whose norm exceeds max_norm is scaled down to it."""
    def fn(a):
        dims = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a.astype(jnp.float32)) ** p,
                        axis=dims, keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return (a * scale.astype(a.dtype))

    return apply(fn, x, name="renorm")


def increment(x, value=1.0, name=None):
    """Add `value` to the single-element tensor x in place and return it
    (reference increment_op.cc — the loop-counter op; works on any
    1-element tensor)."""
    if int(np.prod(x.shape)) != 1:
        raise ValueError(
            f"increment expects a 1-element tensor, got shape {x.shape}")
    x._data = x.data + jnp.asarray(value, dtype=x.data.dtype)
    return x


def tanh_(x, name=None):
    """In-place tanh (reference tanh_ inplace activation)."""
    from ..nn.functional.activation import _inplace, tanh as _tanh
    return _inplace(x, _tanh)
