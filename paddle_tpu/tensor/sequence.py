"""Sequence (LoD) op family on the dense+mask ragged convention.

Reference: /root/reference/paddle/fluid/operators/sequence_ops/ (~35k
LoC of LoD kernels: sequence_pool_op.h, sequence_softmax, sequence_
reverse, sequence_pad/unpad, sequence_expand, sequence_concat,
sequence_enumerate, ...) and fluid/layers/sequence_lod.py.

TPU-native shape: the reference's LoD tensor is a flat value buffer plus
offsets; XLA wants static shapes, so ragged data here is [B, T, ...]
plus per-row lengths, and every sequence op is a masked dense op the
compiler fuses (the same convention text/utils.py and the attention
kv_mask use — this module is the shared helper layer VERDICT asked for).
All ops differentiate through the eager tape and trace into jit.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from ..core.autograd import apply
from ..core.tensor import Tensor, unwrap as _arr

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_reverse",
    "sequence_pad", "sequence_unpad", "sequence_expand",
    "sequence_concat", "sequence_enumerate", "sequence_first_step",
    "sequence_last_step", "sequence_slice",
]

_NEG = -1e30




def _mask(lengths, maxlen):
    pos = jnp.arange(maxlen, dtype=jnp.int32)
    return pos[None, :] < _arr(lengths).astype(jnp.int32)[:, None]


def sequence_pool(x, lengths, pool_type: str = "sum"):
    """Masked pooling over the time dim (sequence_pool_op.h SUM/AVERAGE/
    SQRT/MAX/FIRST/LAST). x: [B, T, ...], lengths: [B]."""
    pool_type = pool_type.lower()

    def fn(xa, la):
        t = xa.shape[1]
        m = _mask(la, t)
        mexp = m.reshape(m.shape + (1,) * (xa.ndim - 2))
        n = jnp.maximum(la.astype(xa.dtype), 1)
        nexp = n.reshape((-1,) + (1,) * (xa.ndim - 2))
        if pool_type == "sum":
            return jnp.where(mexp, xa, 0).sum(axis=1)
        if pool_type in ("average", "mean", "avg"):
            return jnp.where(mexp, xa, 0).sum(axis=1) / nexp
        if pool_type == "sqrt":
            return jnp.where(mexp, xa, 0).sum(axis=1) / jnp.sqrt(nexp)
        if pool_type == "max":
            return jnp.where(mexp, xa, _NEG).max(axis=1)
        if pool_type == "first":
            return xa[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(la.astype(jnp.int32) - 1, 0)
            return jnp.take_along_axis(
                xa, idx.reshape((-1, 1) + (1,) * (xa.ndim - 2)),
                axis=1).squeeze(1)
        raise ValueError(f"unknown pool_type {pool_type!r}")

    return apply(fn, x, Tensor(_arr(lengths)), name="sequence_pool")


def sequence_first_step(x, lengths):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths):
    return sequence_pool(x, lengths, "last")


def sequence_softmax(x, lengths):
    """Per-row softmax over the valid prefix (sequence_softmax_op).
    x: [B, T]; padded positions get probability 0."""
    def fn(xa, la):
        m = _mask(la, xa.shape[1])
        scores = jnp.where(m, xa, _NEG)
        p = jnp.exp(scores - scores.max(axis=1, keepdims=True))
        p = jnp.where(m, p, 0)
        return p / jnp.maximum(p.sum(axis=1, keepdims=True), 1e-30)

    return apply(fn, x, Tensor(_arr(lengths)), name="sequence_softmax")


def sequence_reverse(x, lengths):
    """Reverse each row's valid prefix in place, padding stays put
    (sequence_reverse_op.h). x: [B, T, ...]."""
    def fn(xa, la):
        t = xa.shape[1]
        pos = jnp.arange(t, dtype=jnp.int32)[None, :]
        li = la.astype(jnp.int32)[:, None]
        src = jnp.where(pos < li, li - 1 - pos, pos)  # [B, T]
        src = src.reshape(src.shape + (1,) * (xa.ndim - 2))
        return jnp.take_along_axis(xa, src, axis=1)

    return apply(fn, x, Tensor(_arr(lengths)), name="sequence_reverse")


def sequence_pad(sequences: Sequence, pad_value=0.0,
                 maxlen: Optional[int] = None):
    """List of per-row arrays -> (padded [B, maxlen, ...], lengths [B])
    (sequence_pad_op). Host-side by nature (ragged python input)."""
    seqs = [np.asarray(s) for s in sequences]
    lengths = np.asarray([len(s) for s in seqs], np.int64)
    t = int(maxlen) if maxlen is not None else int(lengths.max())
    tail = seqs[0].shape[1:]
    out = np.full((len(seqs), t) + tail, pad_value,
                  dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        n = min(len(s), t)
        out[i, :n] = s[:n]
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lengths))


def sequence_unpad(x, lengths) -> List[np.ndarray]:
    """Inverse of sequence_pad (sequence_unpad_op): strip padding back
    into a ragged python list. Host-side."""
    xa = np.asarray(_arr(x))
    la = np.asarray(_arr(lengths), np.int64)
    return [xa[i, :int(n)] for i, n in enumerate(la)]


def sequence_expand(x, ref_lengths):
    """Repeat row i ref_lengths[i] times (sequence_expand_op with a
    row-per-sequence ref). Output is ragged-flat [sum(ref), ...] —
    host-side because the output shape is data-dependent."""
    xa = np.asarray(_arr(x))
    la = np.asarray(_arr(ref_lengths), np.int64)
    if len(la) != len(xa):
        raise ValueError(f"ref_lengths has {len(la)} rows, x has "
                         f"{len(xa)}")
    return Tensor(jnp.asarray(np.repeat(xa, la, axis=0)))


def sequence_concat(xs: Sequence, lengths: Sequence):
    """Concatenate ragged rows along time (sequence_concat_op):
    ([B,T1,...],[B,T2,...]) + lengths -> [B, sum(max valid), ...] with
    combined lengths; valid prefixes abut, padding moves to the tail."""
    arrs = [np.asarray(_arr(x)) for x in xs]
    lens = [np.asarray(_arr(l), np.int64) for l in lengths]
    if len(arrs) != len(lens):
        raise ValueError("need one lengths vector per input")
    b = arrs[0].shape[0]
    total = sum(lens)
    t_out = int(total.max())
    tail = arrs[0].shape[2:]
    out = np.zeros((b, t_out) + tail, arrs[0].dtype)
    for i in range(b):
        off = 0
        for a, l in zip(arrs, lens):
            n = int(l[i])
            out[i, off:off + n] = a[i, :n]
            off += n
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(total))


def sequence_enumerate(x, win_size: int, pad_value=0):
    """Sliding windows over each row (sequence_enumerate_op):
    [B, T] -> [B, T, win_size], windows past the end padded."""
    def fn(xa):
        t = xa.shape[1]
        pad = jnp.full(xa.shape[:1] + (win_size - 1,) + xa.shape[2:],
                       pad_value, xa.dtype)
        ext = jnp.concatenate([xa, pad], axis=1)
        cols = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]
        return ext[:, cols]

    return apply(fn, x, name="sequence_enumerate")


def sequence_slice(x, lengths, offset, length):
    """Per-row slice of the valid prefix (sequence_slice_op):
    row i keeps [offset[i], offset[i]+length[i]). Returns ([B, max(length),
    ...], new lengths)."""
    xa = np.asarray(_arr(x))
    off = np.asarray(_arr(offset), np.int64).reshape(-1)
    ln = np.asarray(_arr(length), np.int64).reshape(-1)
    la = np.asarray(_arr(lengths), np.int64).reshape(-1)
    if ((off + ln) > la).any():
        raise ValueError("sequence_slice: offset+length exceeds row "
                         "lengths")
    t_out = int(ln.max())
    tail = xa.shape[2:]
    out = np.zeros((xa.shape[0], t_out) + tail, xa.dtype)
    for i in range(xa.shape[0]):
        out[i, :int(ln[i])] = xa[i, int(off[i]):int(off[i] + ln[i])]
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(ln))
