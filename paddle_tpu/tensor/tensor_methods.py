"""Attach the op library to Tensor as methods + operator overloads.

Reference parity: python/paddle/fluid/dygraph/varbase_patch_methods.py and
math_op_patch.py — the reference monkey-patches VarBase with generated
methods; here the same pattern binds the functional op library.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import (attribute, creation, einsum as einsum_mod, linalg, logic, math,
               manipulation, random, search)

_METHOD_SOURCES = [math, manipulation, logic, search, linalg, attribute,
                   creation, random]

# functions whose first arg is the tensor -> safe to expose as methods
_SKIP = {
    "to_tensor", "zeros", "ones", "full", "arange", "linspace", "logspace",
    "eye", "empty", "meshgrid", "tril_indices", "triu_indices", "assign",
    "rand", "randn", "randint", "randperm", "uniform", "normal", "gaussian",
    "standard_normal", "shape", "scatter_nd", "broadcast_shape", "complex",
    "binomial",
}


def _bind():
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_") or name in _SKIP:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            # only the module's own ops — not helpers it imported
            # (apply, convert_dtype, next_key, ...)
            if getattr(fn, "__module__", None) != mod.__name__:
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    Tensor.einsum = None  # not a method
    del Tensor.einsum


_bind()

# paddle-style extra method aliases
Tensor.mean = math.mean
Tensor.sum = math.sum
Tensor.max = math.max
Tensor.min = math.min
Tensor.matmul = math.matmul
Tensor.mm = math.mm
Tensor.abs = math.abs
Tensor.pow = math.pow
Tensor.add = math.add
Tensor.subtract = math.subtract
Tensor.multiply = math.multiply
Tensor.divide = math.divide
Tensor.reshape = manipulation.reshape
Tensor.reshape_ = manipulation.reshape_
Tensor.transpose = manipulation.transpose
Tensor.flatten = manipulation.flatten
Tensor.squeeze = manipulation.squeeze
Tensor.unsqueeze = manipulation.unsqueeze
Tensor.split = manipulation.split
Tensor.chunk = manipulation.chunk
Tensor.gather = manipulation.gather
Tensor.tile = manipulation.tile
Tensor.expand = manipulation.expand
Tensor.topk = search.topk
Tensor.argmax = search.argmax
Tensor.argmin = search.argmin
Tensor.argsort = search.argsort
Tensor.sort = search.sort
Tensor.norm = linalg.norm


# ---- operator overloads (reference math_op_patch.py) ----------------------

def _swap(fn):
    def op(self, other):
        return fn(other, self)
    return op


Tensor.__add__ = math.add
Tensor.__radd__ = _swap(math.add)
Tensor.__sub__ = math.subtract
Tensor.__rsub__ = _swap(math.subtract)
Tensor.__mul__ = math.multiply
Tensor.__rmul__ = _swap(math.multiply)
Tensor.__truediv__ = math.divide
Tensor.__rtruediv__ = _swap(math.divide)
Tensor.__floordiv__ = math.floor_divide
Tensor.__rfloordiv__ = _swap(math.floor_divide)
Tensor.__mod__ = math.remainder
Tensor.__rmod__ = _swap(math.remainder)
Tensor.__pow__ = math.pow
Tensor.__rpow__ = _swap(math.pow)
Tensor.__matmul__ = math.matmul
Tensor.__rmatmul__ = _swap(math.matmul)
Tensor.__neg__ = math.neg
Tensor.__abs__ = math.abs
Tensor.__invert__ = logic.logical_not
Tensor.__eq__ = logic.equal
Tensor.__ne__ = logic.not_equal
Tensor.__lt__ = logic.less_than
Tensor.__le__ = logic.less_equal
Tensor.__gt__ = logic.greater_than
Tensor.__ge__ = logic.greater_equal
Tensor.__and__ = logic.logical_and
Tensor.__or__ = logic.logical_or
Tensor.__xor__ = logic.logical_xor
