"""Comparison / logical / bitwise ops.

Reference parity: python/paddle/tensor/logic.py (compare_op.cc,
logical_op.cc, bitwise ops).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _cmp(fname, jfn):
    def op(x, y, name=None):
        return apply(jfn, x, y, name=fname)
    op.__name__ = fname
    return op


equal = _cmp("equal", lambda a, b: jnp.equal(a, b))
not_equal = _cmp("not_equal", lambda a, b: jnp.not_equal(a, b))
greater_than = _cmp("greater_than", lambda a, b: jnp.greater(a, b))
greater_equal = _cmp("greater_equal", lambda a, b: jnp.greater_equal(a, b))
less_than = _cmp("less_than", lambda a, b: jnp.less(a, b))
less_equal = _cmp("less_equal", lambda a, b: jnp.less_equal(a, b))
logical_and = _cmp("logical_and", lambda a, b: jnp.logical_and(a, b))
logical_or = _cmp("logical_or", lambda a, b: jnp.logical_or(a, b))
logical_xor = _cmp("logical_xor", lambda a, b: jnp.logical_xor(a, b))
bitwise_and = _cmp("bitwise_and", lambda a, b: jnp.bitwise_and(a, b))
bitwise_or = _cmp("bitwise_or", lambda a, b: jnp.bitwise_or(a, b))
bitwise_xor = _cmp("bitwise_xor", lambda a, b: jnp.bitwise_xor(a, b))


def logical_not(x, name=None):
    return apply(jnp.logical_not, x, name="logical_not")


def bitwise_not(x, name=None):
    return apply(jnp.bitwise_not, x, name="bitwise_not")


def equal_all(x, y, name=None):
    x, y = _t(x), _t(y)
    if tuple(x.shape) != tuple(y.shape):
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.array_equal(x.data, y.data))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = _t(x), _t(y)
    return Tensor(jnp.allclose(x.data, y.data, rtol=float(rtol),
                               atol=float(atol), equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=float(rtol),
                                          atol=float(atol), equal_nan=equal_nan),
                 x, y, name="isclose")


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
