"""paddle_tpu.tensor — the tensor op library (reference:
python/paddle/tensor/__init__.py)."""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .sequence import *  # noqa: F401,F403
from .array import *  # noqa: F401,F403

from ..core.tensor import Tensor, to_tensor, is_tensor  # noqa: F401
