"""TensorArray ops (reference: LoDTensorArray + python array_write /
array_read / array_length / create_array in fluid/layers/tensor.py and
lod_array_length_op.cc / array_read_op / array_write_op).

The reference backs these with a C++ vector<LoDTensor> variable used by
While loops and dynamic RNN/beam-search. Eagerly a plain Python list is
the same thing; inside a traced/compiled region, fixed-trip loops over
stacked tensors (lax.scan in static/control_flow.py) replace the
dynamic array — these ops are the eager/imperative surface.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = ["TensorArray", "create_array", "array_write", "array_read",
           "array_length"]


class TensorArray(list):
    """A list of Tensors (the LoDTensorArray role)."""

    def __init__(self, dtype="float32"):
        super().__init__()
        self.dtype = dtype


def create_array(dtype="float32", initialized_list=None):
    arr = TensorArray(dtype)
    for v in (initialized_list or ()):
        arr.append(v if isinstance(v, Tensor) else to_tensor(v))
    return arr


def _idx(i):
    if isinstance(i, Tensor):
        return int(np.asarray(i.data))
    return int(i)


def array_write(x, i, array=None):
    """Write x at index i, growing the array as needed; returns the
    array (reference array_write_op semantics: i may extend the array
    by exactly one slot)."""
    if array is None:
        array = create_array(getattr(x, "dtype", "float32"))
    i = _idx(i)
    x = x if isinstance(x, Tensor) else to_tensor(x)
    if i < len(array):
        array[i] = x
    elif i == len(array):
        array.append(x)
    else:
        raise IndexError(
            f"array_write index {i} skips slots (len={len(array)})")
    return array


def array_read(array, i):
    i = _idx(i)
    if not 0 <= i < len(array):
        raise IndexError(f"array_read index {i} out of range "
                         f"(len={len(array)})")
    return array[i]


def array_length(array):
    import jax.numpy as jnp
    return Tensor(jnp.asarray(len(array), dtype=jnp.int64))
