"""auto_cast context (reference paddle/amp/auto_cast.py:20 +
fluid/dygraph/amp/auto_cast.py:65-73 white/black lists +
imperative/amp_auto_cast.cc AutoCastInputs)."""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Set

import jax.numpy as jnp

__all__ = ["auto_cast", "amp_guard", "amp_state", "white_list", "black_list",
           "decorate"]

# reference fluid/dygraph/amp/auto_cast.py:65 WHITE_LIST / BLACK_LIST,
# extended with this framework's op names.
WHITE_LIST: Set[str] = {
    "conv2d", "conv1d", "conv3d", "conv2d_transpose", "matmul", "matmul_v2",
    "mul", "linear", "einsum", "bmm", "flash_attention",
    "scaled_dot_product_attention", "lstm", "gru", "rnn_tanh", "rnn_relu",
}
BLACK_LIST: Set[str] = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "cross_entropy2", "log_softmax", "binary_cross_entropy",
    "bce_with_logits", "nll_loss", "kl_div", "layer_norm", "batch_norm",
    "group_norm", "instance_norm", "rms_norm", "reduce_mean", "reduce_sum",
    "mse_loss", "l1_loss", "smooth_l1_loss", "ctc_loss", "cumsum",
    "softplus", "erf", "pow", "norm",
}

_tls = threading.local()


class _AmpState:
    __slots__ = ("enabled", "dtype", "level", "white", "black")

    def __init__(self, enabled, dtype, level, white, black):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.white = white
        self.black = black


def amp_state() -> Optional[_AmpState]:
    return getattr(_tls, "amp", None)


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast parity. level O1 = per-op lists; O2 = cast
    everything float except the black list (pure fp16/bf16)."""
    d = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") else jnp.float16
    white = set(WHITE_LIST)
    black = set(BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    prev = amp_state()
    _tls.amp = _AmpState(bool(enable), d, level, white, black)
    try:
        yield
    finally:
        _tls.amp = prev


amp_guard = auto_cast


def cast_inputs_for_op(name: str, arrs):
    """Called from core.autograd.apply: cast float arrays per the active
    amp policy (the AutoCastInputs hook, amp_auto_cast.cc)."""
    st = amp_state()
    if st is None or not st.enabled or not name:
        return arrs

    def is_float(a):
        return hasattr(a, "dtype") and \
            jnp.issubdtype(a.dtype, jnp.floating)

    if name in st.black:
        return tuple(a.astype(jnp.float32) if is_float(a) and
                     a.dtype != jnp.float32 else a for a in arrs)
    if name in st.white or st.level == "O2":
        return tuple(a.astype(st.dtype) if is_float(a) and
                     a.dtype == jnp.float32 else a for a in arrs)
    return arrs


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts model params to the amp dtype
    (master weights live in the optimizer's fp32 accumulators)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers
