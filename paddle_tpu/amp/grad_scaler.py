"""GradScaler: dynamic loss scaling.

Reference: python/paddle/amp/grad_scaler.py:20 over
fluid/dygraph/amp/loss_scaler.py:27 (AmpScaler) and the C++ state machine
operators/amp/update_loss_scaling_op.cc: scale up by incr_ratio after
incr_every_n_steps finite steps, scale down by decr_ratio after
decr_every_n_nan_or_inf bad steps, skip the update on nan/inf.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True,
                 min_loss_scaling=1.0):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        # floor: a long non-finite streak halves the scale only down to
        # here — an unbounded decay would reach denormals/zero and turn
        # every later gradient into garbage
        self._min_scale = float(min_loss_scaling)
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        # lifetime counters (survive checkpoint/resume via state_dict):
        # finite steps, non-finite steps, optimizer updates skipped
        self._total_good_steps = 0
        self._total_bad_steps = 0
        self._skipped_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def scale(self, loss):
        """Multiply the loss (reference AmpScaler.scale)."""
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        """Divide grads by the scale and detect non-finite values
        (reference check_finite_and_unscale_op). The finiteness check is
        one fused device reduction + a single host sync per step — not a
        per-param bool() round-trip (matches the reference's single
        FoundInfinite output var)."""
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        finite = jnp.asarray(True)
        for p in optimizer._parameters or []:
            if p.grad is None:
                continue
            g = p.grad.data * inv
            finite = finite & jnp.isfinite(g).all()
            p.grad._data = g
        self._found_inf = not bool(finite)
        self._unscaled = True

    def minimize(self, optimizer, loss, *args, **kwargs):
        """Reference AmpScaler.minimize: consumes grads from the caller's
        `scaled.backward()`; runs backward itself only when none happened
        since this scaler's last minimize (never reuses stale grads)."""
        optimizer._ensure_fresh_grads(loss)
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        """Unscale, then step unless non-finite grads were found
        (reference GradScaler.step + update_loss_scaling skip logic)."""
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        else:
            self._skipped_steps += 1
        self._unscaled = False

    def update(self):
        """Dynamic scale adjustment (update_loss_scaling_op.cc state
        machine)."""
        if not self._enable or not self._use_dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._good_steps = 0
            self._bad_steps += 1
            self._total_bad_steps += 1
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio,
                                  self._min_scale)
                self._bad_steps = 0
        else:
            self._bad_steps = 0
            self._good_steps += 1
            self._total_good_steps += 1
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
                "min_loss_scaling": self._min_scale,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps,
                "total_good_steps": self._total_good_steps,
                "total_bad_steps": self._total_bad_steps,
                "skipped_steps": self._skipped_steps,
                "use_dynamic_loss_scaling": self._use_dynamic}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._min_scale = sd.get("min_loss_scaling", self._min_scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)
        self._total_good_steps = sd.get("total_good_steps", 0)
        self._total_bad_steps = sd.get("total_bad_steps", 0)
        self._skipped_steps = sd.get("skipped_steps", 0)

    set_state_dict = load_state_dict


class GradScaler(AmpScaler):
    """paddle.amp.GradScaler parity (grad_scaler.py:20)."""
    pass
