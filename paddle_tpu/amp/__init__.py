"""Automatic mixed precision.

Reference: python/paddle/amp/ (auto_cast.py:20, grad_scaler.py:20), C++
autocast imperative/amp_auto_cast.cc, kernels operators/amp/
{check_finite_and_unscale_op,update_loss_scaling_op}.

TPU-native notes: bf16 is the native mixed-precision dtype (MXU computes
bf16 x bf16 -> fp32) and needs NO loss scaling; fp16 + dynamic loss
scaling is kept for API/semantic parity. The per-op white/black list
casting hooks into core.autograd.apply via the thread-local amp state —
the same interception point as the reference's Tracer AutoCastInputs.
"""
from .auto_cast import (  # noqa: F401
    auto_cast, amp_guard, amp_state, white_list, black_list, decorate)
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401
