"""paddle.framework parity surface (dtype helpers, save/load, seed)."""
from ..core.dtype import get_default_dtype, set_default_dtype  # noqa: F401
from ..core.random import seed  # noqa: F401
from .io import save, load  # noqa: F401
