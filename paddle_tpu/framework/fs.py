"""Filesystem abstraction for checkpoint/save paths — local + HDFS.

Reference: /root/reference/paddle/fluid/framework/io/fs.cc (LocalFS +
HDFS via `hadoop fs` shell commands: _get/_put/exists/mkdir) and
python/paddle/distributed/fleet/utils/fs.py (LocalFS/HDFSClient).

Scheme-dispatched: paths starting with "hdfs://" (or "afs://") go
through the hadoop CLI, everything else is the local filesystem.  Save
paths stage through a local temp file and upload (the reference's
_put-on-close pattern), loads download to a temp file first — so the
pickle/np machinery only ever sees local files.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import tempfile
from contextlib import contextmanager
from typing import List

__all__ = ["LocalFS", "HadoopFS", "get_fs", "open_for_write",
           "open_for_read"]


class LocalFS:
    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str):
        if path:
            os.makedirs(path, exist_ok=True)

    def remove(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def list_dir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def put(self, local: str, dest: str):
        self.makedirs(os.path.dirname(dest))
        os.replace(local, dest)  # atomic on the same filesystem

    def get(self, src: str, local: str):
        shutil.copyfile(src, local)


class HadoopFS:
    """`hadoop fs` CLI wrapper (fs.cc ran the same commands).

    The binary is taken from PADDLE_HADOOP_BIN (default "hadoop") so
    tests and exotic installs can point at their own wrapper."""

    def __init__(self):
        self.bin = os.environ.get("PADDLE_HADOOP_BIN", "hadoop")

    def _run(self, *args, check=True) -> subprocess.CompletedProcess:
        cmd = [self.bin, "fs", *args]
        try:
            return subprocess.run(cmd, capture_output=True, text=True,
                                  check=check, timeout=300)
        except FileNotFoundError:
            raise RuntimeError(
                f"hadoop CLI {self.bin!r} not found; install hadoop or "
                f"set PADDLE_HADOOP_BIN (needed for hdfs:// paths)")

    def exists(self, path: str) -> bool:
        return self._run("-test", "-e", path, check=False).returncode == 0

    def makedirs(self, path: str):
        if path:
            self._run("-mkdir", "-p", path)

    def remove(self, path: str):
        self._run("-rm", "-r", "-f", path)

    def list_dir(self, path: str) -> List[str]:
        out = self._run("-ls", path).stdout
        names = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                names.append(parts[-1].rsplit("/", 1)[-1])
        return sorted(names)

    def put(self, local: str, dest: str):
        self.makedirs(dest.rsplit("/", 1)[0])
        # -f: overwrite, the semantics of os.replace
        self._run("-put", "-f", local, dest)
        os.remove(local)

    def get(self, src: str, local: str):
        self._run("-get", src, local)


_REMOTE_SCHEMES = ("hdfs://", "afs://")


def get_fs(path: str):
    if any(path.startswith(s) for s in _REMOTE_SCHEMES):
        return HadoopFS()
    return LocalFS()


@contextmanager
def open_for_write(path: str, mode: str = "wb"):
    """Yield a local file handle; on clean exit the bytes land at `path`
    atomically (local: tmp+rename; remote: tmp+put)."""
    fs = get_fs(path)
    if isinstance(fs, LocalFS):
        d = os.path.dirname(path)
        fs.makedirs(d)
        tmp = path + ".tmp"
        with open(tmp, mode) as f:
            yield f
        os.replace(tmp, path)
    else:
        fd, tmp = tempfile.mkstemp(suffix=".pdtmp")
        os.close(fd)
        try:
            with open(tmp, mode) as f:
                yield f
            fs.put(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)


@contextmanager
def open_for_read(path: str, mode: str = "rb"):
    fs = get_fs(path)
    if isinstance(fs, LocalFS):
        with open(path, mode) as f:
            yield f
    else:
        fd, tmp = tempfile.mkstemp(suffix=".pdtmp")
        os.close(fd)
        try:
            fs.get(path, tmp)
            with open(tmp, mode) as f:
                yield f
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
