"""Filesystem abstraction for checkpoint/save paths — local + HDFS.

Reference: /root/reference/paddle/fluid/framework/io/fs.cc (LocalFS +
HDFS via `hadoop fs` shell commands: _get/_put/exists/mkdir) and
python/paddle/distributed/fleet/utils/fs.py (LocalFS/HDFSClient).

Scheme-dispatched: paths starting with "hdfs://" (or "afs://") go
through the hadoop CLI, everything else is the local filesystem.  Save
paths stage through a local temp file and upload (the reference's
_put-on-close pattern), loads download to a temp file first — so the
pickle/np machinery only ever sees local files.

Robustness posture (production training treats I/O failure as the
common case):
- every write is flush+fsync'd BEFORE the atomic rename, so a crash can
  never commit a zero-length or partially-written file;
- LocalFS.put survives EXDEV (tmp and dest on different filesystems) by
  falling back to copy + same-directory rename;
- HadoopFS shell-outs and open_for_read/open_for_write retry with
  exponential backoff + jitter (PADDLE_TPU_FS_RETRIES, default 3);
- deterministic chaos via paddle_tpu.testing.faults (PADDLE_FAULT_FS).
"""
from __future__ import annotations

import errno
import os
import random
import shutil
import subprocess
import tempfile
import time
from contextlib import contextmanager
from typing import List

__all__ = ["LocalFS", "HadoopFS", "get_fs", "open_for_write",
           "open_for_read", "retry_with_backoff", "fsync_file"]


def _fault(op: str):
    """Fault point — no-op unless PADDLE_FAULT_FS /
    PADDLE_FAULT_FS_DELAY_MS arms it (delay fires first: a slow THEN
    failing store is the realistic compound fault)."""
    if os.environ.get("PADDLE_FAULT_FS_DELAY_MS"):
        from ..testing import faults
        faults.maybe_delay_fs(op)
    if os.environ.get("PADDLE_FAULT_FS"):
        from ..testing import faults
        faults.maybe_fail_fs(op)


def fsync_file(f):
    """Flush a file object's buffers all the way to stable storage."""
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str):
    """Best-effort durability for a rename: fsync the containing
    directory so the new directory entry survives a crash."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:  # pragma: no cover (exotic fs)
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def retry_with_backoff(fn, *, tries: int = None, base_ms: float = 50.0,
                       max_ms: float = 5000.0, jitter: float = 0.25,
                       retry_on=(OSError, subprocess.SubprocessError),
                       desc: str = "fs op", sleep=time.sleep):
    """Run fn() with exponential backoff + jitter on transient errors.

    tries defaults to PADDLE_TPU_FS_RETRIES (3). The delay before
    attempt k is min(max_ms, base_ms * 2**(k-1)) scaled by a random
    factor in [1, 1+jitter] — the Check-N-Run-style posture that a
    storage hiccup should cost a bounded wait, not the training run.
    """
    if tries is None:
        tries = int(os.environ.get("PADDLE_TPU_FS_RETRIES", "3"))
    tries = max(1, tries)
    for attempt in range(tries):
        try:
            return fn()
        except retry_on:
            if attempt + 1 >= tries:
                raise
            delay = min(max_ms, base_ms * (2 ** attempt)) / 1000.0
            delay *= 1.0 + random.random() * jitter
            sleep(delay)


class LocalFS:
    def exists(self, path: str) -> bool:
        _fault("exists")
        return os.path.exists(path)

    def makedirs(self, path: str):
        _fault("mkdir")
        if path:
            os.makedirs(path, exist_ok=True)

    def remove(self, path: str):
        _fault("remove")
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def list_dir(self, path: str) -> List[str]:
        _fault("list")
        return sorted(os.listdir(path))

    def put(self, local: str, dest: str):
        _fault("put")
        self.makedirs(os.path.dirname(dest))
        try:
            os.replace(local, dest)  # atomic on the same filesystem
        except OSError as e:
            if e.errno != errno.EXDEV:
                raise
            # tmp and dest sit on different filesystems (tmpfs staging
            # dir + NFS checkpoint dir is the classic case): stage a
            # copy NEXT TO dest so the final rename is same-fs atomic
            tmp = dest + ".xdev.tmp"
            with open(local, "rb") as src, open(tmp, "wb") as out:
                shutil.copyfileobj(src, out)
                fsync_file(out)
            os.replace(tmp, dest)
            os.remove(local)
        _fsync_dir(os.path.dirname(dest))

    def get(self, src: str, local: str):
        _fault("get")
        shutil.copyfile(src, local)


class HadoopFS:
    """`hadoop fs` CLI wrapper (fs.cc ran the same commands).

    The binary is taken from PADDLE_HADOOP_BIN (default "hadoop") so
    tests and exotic installs can point at their own wrapper.  Every
    command retries with backoff: a transient namenode hiccup costs a
    bounded wait instead of the training run."""

    def __init__(self):
        self.bin = os.environ.get("PADDLE_HADOOP_BIN", "hadoop")

    def _run_once(self, cmd, check) -> subprocess.CompletedProcess:
        try:
            return subprocess.run(cmd, capture_output=True, text=True,
                                  check=check, timeout=300)
        except FileNotFoundError:
            raise RuntimeError(
                f"hadoop CLI {self.bin!r} not found; install hadoop or "
                f"set PADDLE_HADOOP_BIN (needed for hdfs:// paths)")

    def _run(self, *args, check=True) -> subprocess.CompletedProcess:
        cmd = [self.bin, "fs", *args]

        def attempt():
            _fault("run")
            return self._run_once(cmd, check)

        # CalledProcessError/TimeoutExpired are SubprocessError; the
        # RuntimeError for a missing binary is deliberately NOT retried
        return retry_with_backoff(attempt, desc=f"hadoop {args[0]}")

    def exists(self, path: str) -> bool:
        return self._run("-test", "-e", path, check=False).returncode == 0

    def makedirs(self, path: str):
        if path:
            self._run("-mkdir", "-p", path)

    def remove(self, path: str):
        self._run("-rm", "-r", "-f", path)

    def list_dir(self, path: str) -> List[str]:
        out = self._run("-ls", path).stdout
        names = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                names.append(parts[-1].rsplit("/", 1)[-1])
        return sorted(names)

    def put(self, local: str, dest: str):
        self.makedirs(dest.rsplit("/", 1)[0])
        # -f: overwrite, the semantics of os.replace
        self._run("-put", "-f", local, dest)
        os.remove(local)

    def get(self, src: str, local: str):
        self._run("-get", src, local)


_REMOTE_SCHEMES = ("hdfs://", "afs://")


def get_fs(path: str):
    if any(path.startswith(s) for s in _REMOTE_SCHEMES):
        return HadoopFS()
    return LocalFS()


@contextmanager
def open_for_write(path: str, mode: str = "wb"):
    """Yield a local file handle; on clean exit the bytes land at `path`
    atomically (local: fsync + tmp+rename; remote: fsync + tmp+put).
    A crash mid-write leaves the destination untouched — the fsync
    BEFORE the rename means a committed path can never be zero-length —
    and an exception inside the block removes the temp file instead of
    orphaning it."""
    fs = get_fs(path)
    if isinstance(fs, LocalFS):
        _fault("open_write")
        d = os.path.dirname(path)
        fs.makedirs(d)
        tmp = path + ".tmp"
        try:
            with open(tmp, mode) as f:
                yield f
                fsync_file(f)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        os.replace(tmp, path)
        _fsync_dir(d)
    else:
        _fault("open_write")
        fd, tmp = tempfile.mkstemp(suffix=".pdtmp")
        os.close(fd)
        try:
            with open(tmp, mode) as f:
                yield f
                fsync_file(f)
            fs.put(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)


@contextmanager
def open_for_read(path: str, mode: str = "rb"):
    fs = get_fs(path)
    if isinstance(fs, LocalFS):
        _fault("open_read")
        with open(path, mode) as f:
            yield f
    else:
        _fault("open_read")
        fd, tmp = tempfile.mkstemp(suffix=".pdtmp")
        os.close(fd)
        try:
            fs.get(path, tmp)  # retried inside HadoopFS._run
            with open(tmp, mode) as f:
                yield f
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
