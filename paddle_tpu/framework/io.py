"""paddle.save / paddle.load.

Reference: python/paddle/framework/io.py:201,279 — pickle of (nested)
state dicts with tensors replaced by ndarrays, plus protocol switches.
The C++ fast path (_save_static_dict, pybind.cc:414) is unnecessary
here: jax device_get batches the D2H transfer.

Checkpointing large sharded arrays goes through
paddle_tpu.distributed.checkpoint (orbax-style sharded save) — this
module is the small-object path.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Parameter, Tensor

__all__ = ["save", "load"]

_SENTINEL_KEY = "__paddle_tpu_tensor__"


def _pack(obj: Any):
    if isinstance(obj, Tensor):
        return {_SENTINEL_KEY: True,
                "data": np.asarray(obj.data),
                "name": obj.name,
                "stop_gradient": obj.stop_gradient,
                "is_param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        packed = [_pack(v) for v in obj]
        return t(packed) if t in (list, tuple) else packed
    return obj


def _unpack(obj: Any, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get(_SENTINEL_KEY):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("is_param") else Tensor
            if cls is Parameter:
                t = Parameter(obj["data"], name=obj.get("name"))
            else:
                t = Tensor(obj["data"], name=obj.get("name"),
                           stop_gradient=obj.get("stop_gradient", True))
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        un = [_unpack(v, return_numpy) for v in obj]
        return t(un) if t in (list, tuple) else un
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save parity: state dicts, nested containers, single
    tensors. hdfs:///afs:// paths stage through the fs backend
    (reference framework/io/fs.cc)."""
    from .fs import open_for_write
    with open_for_write(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    """paddle.load parity (local or remote-fs path)."""
    from .fs import open_for_read
    with open_for_read(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
