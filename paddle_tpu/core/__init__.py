from . import autograd, dtype, errors, flags, random  # noqa: F401
from .autograd import (apply, backward, enable_grad, grad, is_grad_enabled,  # noqa: F401
                       no_grad, set_grad_enabled)
from .dtype import (convert_dtype, get_default_dtype, set_default_dtype)  # noqa: F401
from .errors import enforce, EnforceNotMet  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .random import Generator, get_rng_state, seed, set_rng_state  # noqa: F401
from .tensor import Parameter, Tensor, is_tensor, to_tensor  # noqa: F401
