"""Typed error hierarchy + enforce helpers.

TPU-native equivalent of the reference's PADDLE_ENFORCE machinery
(/root/reference/paddle/fluid/platform/enforce.h:440,505 and errors.h /
error_codes.proto). The reference formats typed error codes with stack
traces from C++ macros; here errors are Python exception classes with the
same taxonomy so user-facing behavior matches, and `enforce*` helpers give
call sites the same one-liner ergonomics.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base of all framework errors (reference: platform::EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet, PermissionError):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExternalError(EnforceNotMet):
    """Error from an external library (XLA / PJRT), reference enforce.h:976."""


def enforce(cond, msg="", exc=InvalidArgumentError):
    """PADDLE_ENFORCE equivalent (enforce.h:440)."""
    if not cond:
        raise exc(msg if msg else "Enforce failed.")


def enforce_eq(a, b, msg="", exc=InvalidArgumentError):
    if a != b:
        raise exc(f"Expected {a!r} == {b!r}. {msg}")


def enforce_ne(a, b, msg="", exc=InvalidArgumentError):
    if a == b:
        raise exc(f"Expected {a!r} != {b!r}. {msg}")


def enforce_gt(a, b, msg="", exc=InvalidArgumentError):
    if not a > b:
        raise exc(f"Expected {a!r} > {b!r}. {msg}")


def enforce_ge(a, b, msg="", exc=InvalidArgumentError):
    if not a >= b:
        raise exc(f"Expected {a!r} >= {b!r}. {msg}")


def enforce_lt(a, b, msg="", exc=InvalidArgumentError):
    if not a < b:
        raise exc(f"Expected {a!r} < {b!r}. {msg}")


def enforce_le(a, b, msg="", exc=InvalidArgumentError):
    if not a <= b:
        raise exc(f"Expected {a!r} <= {b!r}. {msg}")


def enforce_not_none(x, name="value", msg="", exc=NotFoundError):
    if x is None:
        raise exc(f"{name} should not be None. {msg}")
    return x
