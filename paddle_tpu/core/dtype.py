"""Dtype registry and defaults.

TPU-native re-design of the reference's numeric type layer
(/root/reference/paddle/fluid/platform/{float16,bfloat16,complex64}.h and
framework.proto VarType.Type): instead of hand-written host types with
intrinsics, dtypes are jnp dtypes with a paddle-style string alias table.
bfloat16 is first-class (TPU MXU native), fp16 is kept for parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Paddle-style names -> jnp dtypes
_DTYPE_ALIASES = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

# canonical exports (usable as paddle_tpu.float32 etc.)
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_default_dtype = jnp.float32


def convert_dtype(dtype):
    """Normalize a dtype spec (string alias, np/jnp dtype, None) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise ValueError(f"Unknown dtype {dtype!r}; known: {sorted(_DTYPE_ALIASES)}")
        return np.dtype(_DTYPE_ALIASES[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """Paddle-style short name for a dtype."""
    d = np.dtype(dtype)
    return d.name


def set_default_dtype(dtype):
    """Set the global default float dtype (paddle.set_default_dtype parity,
    reference: python/paddle/framework/framework.py)."""
    global _default_dtype
    d = convert_dtype(dtype)
    if d.kind != "f":
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    return np.dtype(_default_dtype).name


def default_float_dtype():
    return _default_dtype


def is_floating(dtype) -> bool:
    return np.dtype(dtype).kind == "f"


def is_integer(dtype) -> bool:
    return np.dtype(dtype).kind in ("i", "u")
