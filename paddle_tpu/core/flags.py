"""Global runtime flag registry.

TPU-native equivalent of the reference's gflags registry
(/root/reference/paddle/fluid/platform/flags.cc:33-565) and its Python
surface paddle.set_flags/get_flags
(/root/reference/python/paddle/fluid/framework.py:5822,5845).

Flags are typed, documented, env-overridable (FLAGS_<name>), and looked up
at runtime by subsystems (nan/inf checking, deterministic ops, allocator
staging sizes, logging verbosity). The CUDA-specific flags of the reference
(gpu memory fraction, cudnn knobs) become TPU/XLA-relevant knobs.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    validator: Optional[Callable[[Any], bool]] = None


class FlagRegistry:
    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._values: Dict[str, Any] = {}
        self._lock = threading.RLock()

    def define(self, name, default, help="", type=None, validator=None):
        with self._lock:
            t = type if type is not None else default.__class__
            self._flags[name] = _Flag(name, default, t, help, validator)
            env = os.environ.get("FLAGS_" + name)
            self._values[name] = self._parse(t, env) if env is not None else default

    @staticmethod
    def _parse(t, s):
        if t is bool:
            return s.strip().lower() in ("1", "true", "yes", "on")
        return t(s)

    def set(self, name, value):
        with self._lock:
            if name not in self._flags:
                from .errors import NotFoundError
                raise NotFoundError(f"Unknown flag {name!r}")
            f = self._flags[name]
            if f.validator is not None and not f.validator(value):
                from .errors import InvalidArgumentError
                raise InvalidArgumentError(f"Invalid value {value!r} for flag {name}")
            if isinstance(value, f.type):
                self._values[name] = value
            elif isinstance(value, str):
                # same semantics as env parsing: "false"/"0" disable bools
                self._values[name] = self._parse(f.type, value)
            else:
                self._values[name] = f.type(value)

    def get(self, name):
        with self._lock:
            if name not in self._values:
                from .errors import NotFoundError
                raise NotFoundError(f"Unknown flag {name!r}")
            return self._values[name]

    def has(self, name):
        return name in self._flags

    def all(self):
        with self._lock:
            return dict(self._values)


GLOBAL_FLAGS = FlagRegistry()
_D = GLOBAL_FLAGS.define

# Mirrors of the reference's behavioral flags (platform/flags.cc), TPU-relevant subset.
_D("check_nan_inf", False, "Scan op outputs for NaN/Inf after each eager op "
   "(reference flags.cc:44 -> nan_inf_utils_detail.cc).")
_D("benchmark", False, "Synchronize after each eager op for timing (flags.cc).")
_D("paddle_num_threads", 1, "Host compute threads for dataloader workers.")
_D("eager_delete_tensor_gb", 0.0, "Kept for parity; XLA manages HBM lifetime.")
_D("use_system_allocator", False, "Kept for parity.")
_D("allocator_strategy", "auto_growth", "Host staging allocator strategy "
   "(naive_best_fit|auto_growth), reference allocator_strategy.cc.")
_D("fraction_of_gpu_memory_to_use", 0.92, "Parity alias; on TPU maps to "
   "XLA preallocation fraction.")
_D("init_allocated_mem", False, "Fill freshly allocated host staging buffers.")
_D("cpu_deterministic", False, "Force deterministic reductions.")
_D("max_inplace_grad_add", 0, "Eager grad accumulation chunking (parity).")
_D("call_stack_level", 1, "Error message verbosity (1=user frames, 2=full).")
_D("sort_sum_gradient", False, "Deterministic gradient accumulation order "
   "(reference gradient_accumulator.cc).")
_D("retain_grad_for_all_tensor", False, "Keep .grad on non-leaf tensors.")
_D("tpu_matmul_precision", "default", "jax matmul precision: default|high|highest.")
_D("log_level", 0, "VLOG-style verbosity.")
_D("prim_all", False, "Reserved: decompose ops to primitives.")


def set_flags(flags: dict):
    """paddle.set_flags parity (fluid/framework.py:5822)."""
    for k, v in flags.items():
        name = k[6:] if k.startswith("FLAGS_") else k
        GLOBAL_FLAGS.set(name, v)


def get_flags(flags):
    """paddle.get_flags parity (fluid/framework.py:5845)."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        name = k[6:] if k.startswith("FLAGS_") else k
        out[k] = GLOBAL_FLAGS.get(name)
    return out
