"""Tensor: the user-facing array type.

TPU-native re-design of the reference Tensor/LoDTensor/VarBase stack
(/root/reference/paddle/fluid/framework/tensor.h:89,
imperative/layer.h VarBase): instead of a strided device buffer plus a
separate grad Variable, a Tensor is a thin handle over an immutable
jax.Array (XLA-managed HBM — no user-space allocator needed, reference
memory/allocation/allocator_facade.h is subsumed by the runtime) carrying
autograd metadata (stop_gradient, creator GradNode, accumulated .grad).

Tensors are registered as a jax pytree node so they flow through jit /
grad / shard_map; the autograd tape (core.autograd) is the eager path and
is bypassed under tracing.

LoD (level-of-detail variable-length sequences, lod_tensor.h:114) is NOT
carried on the tensor: TPU/XLA wants static shapes, so variable-length
data uses dense padding + masks (see paddle_tpu.text utilities), which is
the idiomatic equivalent.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .dtype import convert_dtype, default_float_dtype
from .errors import InvalidArgumentError, PreconditionNotMetError

_tensor_counter = 0


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_creator", "name",
                 "persistable", "trainable", "_retain_grads", "__weakref__",
                 "__dict__")

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None,
                 _creator=None, persistable: bool = False):
        global _tensor_counter
        if isinstance(data, Tensor):
            data = data._data
        elif not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = bool(stop_gradient)
        self.grad = None
        self._creator = _creator
        if name is None:
            name = f"generated_tensor_{_tensor_counter}"
            _tensor_counter += 1
        self.name = name
        self.persistable = persistable
        self.trainable = not stop_gradient
        self._retain_grads = False

    # ---- raw array access -------------------------------------------------
    @property
    def data(self):
        return self._data

    @data.setter
    def data(self, value):
        self._data = value.data if isinstance(value, Tensor) else jnp.asarray(value)

    # ---- shape & dtype ----------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    def dim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    def numel(self):
        return self.size

    @property
    def T(self):
        from .autograd import apply
        return apply(lambda a: a.T, self, name="transpose")

    @property
    def place(self):
        devs = getattr(self._data, "devices", None)
        if callable(devs):
            ds = list(devs())
            return ds[0] if len(ds) == 1 else ds
        return None

    @property
    def is_leaf(self):
        return self._creator is None

    # ---- host transfer ----------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return self._data.item(*args) if args else self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def cpu(self):
        return self

    def pin_memory(self):
        return self

    # ---- autograd ---------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def retain_grads(self):
        self._retain_grads = True

    def _accumulate_grad(self, g):
        from .selected_rows import SelectedRows
        # leaf grads live in the leaf's dtype (AMP: ops may run bf16 but a
        # fp32 master param accumulates fp32 grads, like the reference's
        # cast-op backward restoring the source dtype)
        if hasattr(g, "dtype") and g.dtype != self._data.dtype and \
                jnp.issubdtype(g.dtype, jnp.floating) and \
                jnp.issubdtype(self._data.dtype, jnp.floating):
            g = g.astype(self._data.dtype)
        # row-sparse grads (SelectedRows, reference selected_rows.h) stay
        # sparse as long as every contribution is sparse; any dense
        # contribution densifies the accumulated grad
        prev = self.grad
        if isinstance(g, SelectedRows):
            if prev is None:
                self.grad = g
            elif isinstance(prev, SelectedRows):
                self.grad = prev + g
            else:
                self.grad = Tensor(prev._data + g, stop_gradient=True,
                                   name=self.name + "@GRAD")
        elif isinstance(prev, SelectedRows):
            self.grad = Tensor(prev + g, stop_gradient=True,
                               name=self.name + "@GRAD")
        elif prev is None:
            self.grad = Tensor(g, stop_gradient=True, name=self.name + "@GRAD")
        else:
            self.grad = Tensor(prev._data + g, stop_gradient=True,
                               name=self.name + "@GRAD")
        # Stamp which backward pass wrote this grad, so each optimizer's
        # minimize() can tell ITS grads are fresh (a global epoch would let
        # optimizer B's backward mask optimizer A's stale grads). +1 because
        # BACKWARD_EPOCH increments after the engine run: engine-written
        # grads must never share epoch 0 with manually-assigned ones.
        self.grad._bw_epoch = autograd.BACKWARD_EPOCH + 1

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._creator = None
        self.stop_gradient = True
        return self

    def clone(self):
        return autograd.apply(lambda a: a + 0, self, name="clone")

    # ---- in-place-style setters (functional under the hood) ---------------
    def set_value(self, value):
        value = value.data if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(value.shape) != tuple(self._data.shape):
            raise InvalidArgumentError(
                f"set_value shape mismatch: {value.shape} vs {self._data.shape}")
        self._data = value.astype(self._data.dtype)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    # ---- casting ----------------------------------------------------------
    def astype(self, dtype):
        d = convert_dtype(dtype)
        return autograd.apply(lambda a: a.astype(d), self, name="cast")

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        # accepts dtype and/or device; device moves are explicit on TPU
        out = self
        for a in list(args) + list(kwargs.values()):
            try:
                d = convert_dtype(a)
            except (ValueError, TypeError):
                d = None
            if d is not None:
                out = out.astype(d)
        return out

    # ---- indexing ---------------------------------------------------------
    def __getitem__(self, idx):
        idx = tuple(i.data if isinstance(i, Tensor) else i for i in idx) \
            if isinstance(idx, tuple) else (idx.data if isinstance(idx, Tensor) else idx)
        return autograd.apply(lambda a: a[idx], self, name="getitem")

    def __setitem__(self, idx, value):
        idx = tuple(i.data if isinstance(i, Tensor) else i for i in idx) \
            if isinstance(idx, tuple) else (idx.data if isinstance(idx, Tensor) else idx)
        v = value.data if isinstance(value, Tensor) else value
        self._data = self._data.at[idx].set(v)

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ---- python protocol --------------------------------------------------
    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __index__(self):
        return int(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __hash__(self):
        return id(self)

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {np.asarray(self._data)!r})")

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return repr(self)


# NOTE: aux data must be semantic-only (no per-tensor generated names) so
# same-shaped Tensors share a treedef — otherwise every jit call retraces.
def _tensor_flatten(t: Tensor):
    return (t._data,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    (sg,) = aux
    (data,) = children
    t = Tensor.__new__(Tensor)
    t._data = data
    t.stop_gradient = sg
    t.grad = None
    t._creator = None
    t.name = "tensor"
    t.persistable = False
    t.trainable = not sg
    t._retain_grads = False
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


class Parameter(Tensor):
    """Trainable tensor (reference: framework.py Parameter; VarBase with
    persistable=True, stop_gradient=False)."""

    def __init__(self, data, name=None, trainable: bool = True):
        super().__init__(data, stop_gradient=not trainable, name=name,
                         persistable=True)

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._data,), (p.stop_gradient,)),
    lambda aux, children: _tensor_unflatten(aux, children),
)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity."""
    if isinstance(data, Tensor):
        arr = data._data
    else:
        arr = data
    d = convert_dtype(dtype)
    if not isinstance(arr, jax.Array):
        np_arr = np.asarray(arr)
        if d is None and np_arr.dtype == np.float64:
            d = default_float_dtype()
        arr = jnp.asarray(np_arr, dtype=d)
    elif d is not None:
        arr = arr.astype(d)
    return Tensor(arr, stop_gradient=stop_gradient)


def unwrap(x):
    """Tensor -> jax array; anything else through jnp.asarray. The one
    shared unwrap helper (several op modules used to carry private
    copies)."""
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def is_tensor(x):
    return isinstance(x, Tensor)
