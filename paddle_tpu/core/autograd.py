"""Eager autograd: tape + reverse engine.

TPU-native re-design of the reference's imperative runtime:
- Tracer::TraceOp (/root/reference/paddle/fluid/imperative/tracer.cc:132)
  recorded a grad-op node per executed op; here `apply()` records a GradNode
  whose backward is the op's jax.vjp closure (XLA computes the actual VJP,
  no per-op hand-written grad kernels needed).
- BasicEngine (/root/reference/paddle/fluid/imperative/basic_engine.cc:39,265)
  walked grad nodes from the loss; here `backward()` drains nodes in reverse
  creation order (a heap over monotone node ids — same effect as the
  reference's dependency counting) and accumulates leaf grads like
  gradient_accumulator.cc.

The compiled path (paddle_tpu.jit.to_static / trainers) bypasses this tape
entirely and uses jax.grad over pure functions — the tape exists for
dygraph-style usability; jit is the performance path.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .errors import EnforceNotMet, InvalidArgumentError, PreconditionNotMetError
from .flags import GLOBAL_FLAGS

_node_counter = itertools.count()
_tls = threading.local()

# Monotone count of completed reverse passes. Optimizer.minimize uses it
# to distinguish "user already ran loss.backward() for THIS iteration"
# from "grads are stale leftovers" (reference dygraph minimize collects
# grads; it must not silently reuse last iteration's).
BACKWARD_EPOCH = 0


def _grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


def is_grad_enabled() -> bool:
    return _grad_enabled()


class set_grad_enabled:
    """paddle.set_grad_enabled parity; usable as context manager."""

    def __init__(self, mode: bool):
        self.prev = _grad_enabled()
        _tls.grad_enabled = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self.prev
        return False


class no_grad:
    """paddle.no_grad parity: context manager AND decorator."""

    def __enter__(self):
        self.prev = _grad_enabled()
        _tls.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self.prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self.prev = _grad_enabled()
        _tls.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self.prev
        return False


class GradNode:
    """One recorded op application. vjp_fn maps output cotangents ->
    input cotangents (aligned with `inputs`). `fn`/`raw_args` keep the
    forward recipe so create_graph=True can RE-derive the vjp through
    the tape (reference partial_grad_engine.cc re-runs grad ops the
    same way); the arrays cost nothing extra — the vjp closure already
    pins the same residuals."""

    __slots__ = ("id", "vjp_fn", "inputs", "out_avals", "name", "multi",
                 "fn", "raw_args", "__weakref__")

    def __init__(self, vjp_fn, inputs, out_avals, name="", multi=False,
                 fn=None, raw_args=None):
        self.id = next(_node_counter)
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list[Tensor]
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.name = name
        self.multi = multi  # forward returned a tuple/list (even of len 1)
        self.fn = fn
        self.raw_args = raw_args

    def __repr__(self):
        return f"<GradNode {self.name or 'op'} id={self.id}>"


def _static_mode_on() -> bool:
    """Fast check for paddle.enable_static without importing the static
    package on the eager hot path."""
    import sys
    mod = sys.modules.get("paddle_tpu.static.program")
    return mod is not None and mod.in_static_mode()


def _check_nan_inf(arrs, name):
    # FLAGS_check_nan_inf parity (reference nan_inf_utils_detail.cc:293).
    # Eager values only: under a jit trace the values are symbolic —
    # compiled coverage is SpmdTrainer's in-step guard, which returns a
    # finite-check vector from the executable instead.
    for a in arrs:
        if isinstance(a, jax.core.Tracer):
            return
        if hasattr(a, "dtype") and jax.numpy.issubdtype(
                a.dtype, jax.numpy.floating):
            if not bool(jax.numpy.isfinite(a).all()):
                raise EnforceNotMet(
                    f"Operator {name or 'op'} output contains NaN or Inf.")


def apply(fn, *args, name: str = ""):
    """Run `fn` over the unwrapped arrays of `args`, recording a GradNode if
    any input Tensor wants gradients. Non-Tensor args pass through
    undifferentiated. Returns Tensor or tuple of Tensors mirroring fn's
    output structure.

    Static mode (paddle.enable_static): ops over static Variables record
    graph nodes onto the default Program instead of executing — the
    trace-based replacement for the reference's op-desc append.
    """
    from .tensor import Tensor

    if _static_mode_on():
        from ..static.program import maybe_record
        rec = maybe_record(fn, args, name)
        if rec is not None:
            return rec

    arrs = tuple(a.data if isinstance(a, Tensor) else a for a in args)

    # AMP autocast hook (reference Tracer::TraceOp -> AutoCastInputs,
    # imperative/amp_auto_cast.cc). Import is deferred and state checked
    # cheaply so the non-AMP path pays one attribute lookup.
    from ..amp.auto_cast import amp_state, cast_inputs_for_op
    if amp_state() is not None:
        arrs = cast_inputs_for_op(name, arrs)

    needs_grad = _grad_enabled() and any(
        isinstance(a, Tensor) and not a.stop_gradient for a in args
    )

    if needs_grad:
        out, vjp_fn = jax.vjp(fn, *arrs)
    else:
        out = fn(*arrs)
        vjp_fn = None

    multi = isinstance(out, (tuple, list))
    outs = tuple(out) if multi else (out,)

    if GLOBAL_FLAGS.get("check_nan_inf"):
        _check_nan_inf(outs, name)

    if vjp_fn is None:
        wrapped = tuple(Tensor(o, stop_gradient=True) for o in outs)
    else:
        tensor_inputs = [a if isinstance(a, Tensor) else None for a in args]
        node = GradNode(
            vjp_fn,
            tensor_inputs,
            [(getattr(o, "shape", ()), getattr(o, "dtype", None)) for o in outs],
            name=name or getattr(fn, "__name__", ""),
            multi=multi,
            fn=fn,
            raw_args=arrs,
        )
        wrapped = tuple(
            Tensor(o, stop_gradient=False, _creator=(node, i))
            for i, o in enumerate(outs)
        )
    return wrapped if multi else wrapped[0]


def _accumulate(dst, val):
    return val if dst is None else dst + val


def _is_float0(x):
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _run_engine(roots, root_grads, retain_graph=False, accumulate_leaf=True,
                capture: Optional[dict] = None, create_graph=False):
    """Core reverse pass. `capture`: id(tensor) -> slot dict to collect grads
    for paddle.grad()-style calls instead of (or in addition to) writing
    .grad on leaves. With create_graph=True every vjp application is
    itself recorded through `apply` (re-deriving it from the node's
    saved forward fn), so the produced gradients carry tape history and
    can be differentiated again — double grad, reference
    partial_grad_engine.cc."""
    from .tensor import Tensor

    # node -> {out_idx: cotangent}
    pending: dict = {}
    heap: List[Tuple[int, GradNode]] = []
    seen = set()

    def push(node, idx, cot):
        slots = pending.setdefault(node, {})
        slots[idx] = _accumulate(slots.get(idx), cot)
        if node.id not in seen:
            seen.add(node.id)
            heapq.heappush(heap, (-node.id, node))

    retain_all = GLOBAL_FLAGS.get("retain_grad_for_all_tensor")

    for root, g in zip(roots, root_grads):
        if root.stop_gradient:
            raise PreconditionNotMetError(
                "backward() on a tensor with stop_gradient=True")
        if root._creator is not None:
            node, idx = root._creator
            push(node, idx, Tensor(g, stop_gradient=True)
                 if create_graph else g)
        else:
            root._accumulate_grad(g)

    def _arr(x):
        return x.data if isinstance(x, Tensor) else x

    while heap:
        _, node = heapq.heappop(heap)
        slots = pending.pop(node)
        cots = []
        for i, (shape, dtype) in enumerate(node.out_avals):
            c = slots.get(i)
            if c is None:
                c = jax.numpy.zeros(shape, dtype)
                if create_graph:
                    c = Tensor(c, stop_gradient=True)
            elif dtype is not None and getattr(c, "dtype", None) != dtype:
                # mixed-precision boundary (AMP): downstream ops may have
                # produced cotangents in their compute dtype; vjp demands
                # the recorded output dtype
                c = c.astype(dtype)
            cots.append(c)
        if node.vjp_fn is None:
            raise PreconditionNotMetError(
                "Trying to backward through the graph a second time; "
                "set retain_graph=True if you need to.")
        if create_graph and node.fn is not None:
            in_grads = _vjp_through_tape(node, cots)
        else:
            # cotangent structure must mirror the forward output
            # structure exactly (1-element tuple output -> 1-element cot)
            out = tuple(_arr(c) for c in cots) if node.multi \
                else _arr(cots[0])
            in_grads = node.vjp_fn(out)
        if not retain_graph and not create_graph:
            node.vjp_fn = None
        for t, g in zip(node.inputs, in_grads):
            if t is None or t.stop_gradient or _is_float0(g):
                continue
            if capture is not None and id(t) in capture:
                capture[id(t)]["grad"] = _accumulate(capture[id(t)].get("grad"), g)
                if t._creator is None and not accumulate_leaf:
                    continue
            if t._creator is not None:
                cnode, cidx = t._creator
                push(cnode, cidx, g)
                if retain_all or t._retain_grads:
                    t._accumulate_grad(_arr(g))
            elif accumulate_leaf:
                t._accumulate_grad(_arr(g))


def _vjp_through_tape(node, cots):
    """Re-derive a node's vjp THROUGH `apply` so the produced gradients
    carry tape history (create_graph=True). The node's original Tensor
    inputs enter as apply arguments, which is what connects d(grad)/dx
    to x in the second-order graph."""
    n_args = len(node.raw_args)

    def vjp_recompute(*flat):
        args, cot = flat[:n_args], flat[n_args:]
        _, f_vjp = jax.vjp(node.fn, *args)
        gs = f_vjp(tuple(cot) if node.multi else cot[0])
        return tuple(gs)

    ins = [t if t is not None else a
           for t, a in zip(node.inputs, node.raw_args)]
    out = apply(vjp_recompute, *ins, *cots,
                name=(node.name or "op") + "_grad")
    return out if isinstance(out, tuple) else (out,)


def backward(tensor, grad_tensor=None, retain_graph=False):
    """Tensor.backward() implementation (reference
    varbase_patch_methods.py:136 -> BasicEngine::Execute)."""
    import jax.numpy as jnp
    from .tensor import Tensor

    if grad_tensor is None:
        if tensor.size != 1:
            g = jnp.ones(tensor.data.shape, tensor.data.dtype)
        else:
            g = jnp.ones_like(tensor.data)
    else:
        g = grad_tensor.data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    _run_engine([tensor], [g], retain_graph=retain_graph)
    global BACKWARD_EPOCH
    BACKWARD_EPOCH += 1


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad parity (reference partial_grad_engine.cc:1064).
    create_graph=True records the backward pass itself on the tape, so
    the returned gradients can be differentiated again (double grad).
    """
    import jax.numpy as jnp
    from .tensor import Tensor

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [jnp.ones_like(o.data) for o in outputs]
    else:
        grad_outputs = [
            jnp.ones_like(o.data) if g is None else (g.data if isinstance(g, Tensor) else jnp.asarray(g))
            for o, g in zip(outputs, grad_outputs)
        ]
    capture = {id(t): {} for t in inputs}
    # create_graph implies the graph survives (reference semantics)
    retain = bool(retain_graph) if retain_graph is not None \
        else bool(create_graph)
    _run_engine(outputs, grad_outputs, retain_graph=retain,
                accumulate_leaf=False, capture=capture,
                create_graph=create_graph)
    results = []
    for t in inputs:
        g = capture[id(t)].get("grad")
        if g is None and not allow_unused:
            raise InvalidArgumentError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph; pass allow_unused=True to return None.")
        if g is None:
            results.append(None)
        elif create_graph:
            # keep the tape connection: the grad is itself differentiable
            results.append(g if isinstance(g, Tensor) else Tensor(g))
        else:
            results.append(Tensor(g.data if isinstance(g, Tensor) else g,
                                  stop_gradient=True))
    return results
