"""SelectedRows — row-sparse gradients for large-vocab embeddings.

Reference: /root/reference/paddle/fluid/framework/selected_rows.h (the
(rows, value) pair that lookup_table's backward emits when is_sparse),
operators/math/selected_rows_functor.cc MergeAdd (unique-ids + row sum),
and the sparse optimizer functors (adam_op.h SparseAdamFunctor,
sgd_op.h sparse branch).

TPU-native shape: `rows` [n] int32 + `values` [n, dim] jax arrays.
merge() is the MergeAdd role — jnp.unique + segment-sum — and produces
the canonical deduplicated form the sparse optimizer fast paths consume;
`to_dense()` is a single scatter-add.  The eager embedding op emits one
of these instead of densifying the full [vocab, dim] table every step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "embedding_sparse"]


class SelectedRows:
    """Row-sparse tensor: values[i] belongs to full row rows[i].

    Rows may repeat (the raw backward emits one entry per looked-up id);
    merge() deduplicates.  Supports `+` against other SelectedRows
    (cheap concat, the accumulation path) and against dense arrays.
    """

    __slots__ = ("rows", "values", "full_shape", "_bw_epoch")

    def __init__(self, rows, values, full_shape):
        self.rows = jnp.asarray(rows, jnp.int32)
        self.values = values
        self.full_shape = tuple(full_shape)
        if self.values.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"values rows {self.values.shape[0]} != ids "
                f"{self.rows.shape[0]}")
        if tuple(self.values.shape[1:]) != self.full_shape[1:]:
            raise ValueError(
                f"value row shape {self.values.shape[1:]} != dense row "
                f"shape {self.full_shape[1:]}")

    # ---- array-protocol bits the autograd engine touches ---------------
    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return self.full_shape

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype),
                            self.full_shape)

    def is_selected_rows(self) -> bool:
        return True

    # ---- conversions ----------------------------------------------------
    def merge(self) -> "SelectedRows":
        """Deduplicate rows (MergeAdd, selected_rows_functor.cc): unique
        ids + segment-sum of their values."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.full_shape[0])
        summed = jax.ops.segment_sum(self.values, inv.reshape(-1),
                                     num_segments=uniq.shape[0])
        # unique() padding (fill_value = vocab) marks unused slots; keep
        # them — scatter with mode='drop' ignores out-of-range rows
        return SelectedRows(uniq, summed, self.full_shape)

    def to_dense(self):
        dense = jnp.zeros(self.full_shape, self.values.dtype)
        return dense.at[self.rows].add(self.values, mode="drop")

    def numpy(self):
        import numpy as np
        return np.asarray(self.to_dense())

    # ---- arithmetic (gradient accumulation) -----------------------------
    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.full_shape != self.full_shape:
                raise ValueError("SelectedRows shape mismatch: "
                                 f"{self.full_shape} vs {other.full_shape}")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.full_shape)
        # dense + sparse -> dense
        return jnp.asarray(other).at[self.rows].add(
            self.values.astype(other.dtype), mode="drop")

    __radd__ = __add__

    def __repr__(self):
        return (f"SelectedRows(rows={self.rows.shape[0]}, "
                f"full_shape={self.full_shape}, dtype={self.dtype})")


def embedding_sparse(x, weight, padding_idx=None):
    """Eager embedding lookup whose weight gradient is a SelectedRows.

    Reference lookup_table_v2_op.cc with is_sparse=True: forward is the
    usual gather; backward emits (ids, upstream-grad-rows) instead of
    scattering into a dense [vocab, dim] zero table.  The tape node is
    hand-built because jax.vjp can only produce dense cotangents.
    """
    from .autograd import GradNode, _grad_enabled
    from .tensor import Tensor

    ids = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    ids = ids.astype(jnp.int32)
    w_t = weight if isinstance(weight, Tensor) else None
    w = weight.data if isinstance(weight, Tensor) else jnp.asarray(weight)
    vocab, dim = w.shape

    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)

    needs = _grad_enabled() and w_t is not None and not w_t.stop_gradient
    if not needs:
        return Tensor(out, stop_gradient=True)

    def vjp_fn(g):
        rows = ids.reshape(-1)
        vals = jnp.asarray(g).reshape(-1, dim)
        if padding_idx is not None:
            vals = jnp.where((rows == padding_idx)[:, None], 0.0, vals)
        return (None, SelectedRows(rows, vals, (vocab, dim)))

    node = GradNode(vjp_fn, [None, w_t],
                    [(tuple(out.shape), out.dtype)],
                    name="embedding_sparse_grad", multi=False,
                    fn=None, raw_args=(ids, w))
    return Tensor(out, stop_gradient=False, _creator=(node, 0))
