"""Random state management.

The reference seeds per-device cuRAND generators (Program.random_seed,
paddle.seed — /root/reference/python/paddle/fluid/framework.py and
framework/generator.cc). JAX randomness is functional (explicit PRNG keys),
so this module bridges the two worlds:

- A global stateful `Generator` gives paddle-style implicit randomness for
  eager mode (`paddle_tpu.seed(n)`; each random op draws a fresh subkey).
- `rng_guard(key)` pushes an explicit key stack used while tracing pure
  functions (jit/to_static/train steps), so compiled code gets traced key
  arguments instead of baked-in constants.
"""
from __future__ import annotations

import threading

import jax


class Generator:
    """Key creation is LAZY: building a jax PRNG key initializes the XLA
    backend, and `import paddle_tpu` must not do that — the reference
    contract is `import paddle; init_parallel_env()`, and
    jax.distributed.initialize only works BEFORE first backend use."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._key = None

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(int(seed))
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def _ensure_key(self):
        """Lazy init under the lock (callers must hold self._lock)."""
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def next_key(self):
        with self._lock:
            self._ensure_key()
            self._key, sub = jax.random.split(self._key)
            return sub

    def state(self):
        with self._lock:
            return self._ensure_key()


_default_generator = Generator(0)
_tls = threading.local()


def default_generator() -> Generator:
    return _default_generator


def seed(n: int) -> Generator:
    """paddle.seed parity."""
    return _default_generator.manual_seed(n)


def get_rng_state():
    return _default_generator.state()


def set_rng_state(key):
    _default_generator._key = key


class rng_guard:
    """Push an explicit PRNG key for the duration of a trace.

    While active, `next_key()` derives keys by folding a counter into the
    pushed key — fully traceable, so dropout etc. stay random across steps
    when the key is a function argument.
    """

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append([self._key, 0])
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()
        return False


def has_explicit_key() -> bool:
    return bool(getattr(_tls, "stack", None))


def next_key():
    """Draw a PRNG key: from the innermost rng_guard if active, else the
    global generator."""
    stack = getattr(_tls, "stack", None)
    if stack:
        entry = stack[-1]
        key = jax.random.fold_in(entry[0], entry[1])
        entry[1] += 1
        return key
    return _default_generator.next_key()
