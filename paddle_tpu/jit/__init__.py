"""paddle.jit parity: to_static / save / load.

Reference: fluid/dygraph/dygraph_to_static/ (ProgramTranslator:756,
StaticFunction/@to_static:233, PartialProgramLayer) + paddle.jit.save/load
via TranslatedLayer.

TPU-native: AST transformation is unnecessary — jax traces the Python
directly (paddle_tpu.func.functional_call) and XLA compiles the whole
step. `save` exports the compiled function as serialized StableHLO
(jax.export) + a pickled state dict; `load` returns a TranslatedLayer
that calls the deserialized executable — the analogue of
save_inference_model + AnalysisPredictor for the common path.
"""
from .api import (  # noqa: F401
    to_static, not_to_static, StaticFunction, save, load, TranslatedLayer,
    in_tracing, enable_to_static)
