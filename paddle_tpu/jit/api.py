"""to_static: compile a Layer or function with XLA.

Reference mapping:
- @to_static / StaticFunction (program_translator.py:233): here a wrapper
  that traces forward through paddle_tpu.func.functional_call and caches
  one jax.jit executable per (input shapes/dtypes, training flag).
- PartialProgramLayer (runs the static block inside dygraph, with grads):
  here the jitted pure function participates in the eager tape via
  core.autograd.apply over (params, buffers, inputs) — backward gets the
  XLA-compiled VJP, so train loops keep working unchanged.
- RNG: dropout keys become traced arguments (core.random.rng_guard), so
  randomness stays fresh across compiled steps instead of baking in.
"""
from __future__ import annotations

import functools
import os
import pickle
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as prandom
from ..core.autograd import apply as tape_apply
from ..core.tensor import Parameter, Tensor
from ..func import functional_state
from ..nn.layer_base import Layer

__all__ = ["to_static", "not_to_static", "StaticFunction", "save", "load",
           "TranslatedLayer", "in_tracing", "enable_to_static"]

_tls = threading.local()
_to_static_enabled = True


def enable_to_static(flag: bool):
    """ProgramTranslator().enable(False) parity."""
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def in_tracing() -> bool:
    return bool(getattr(_tls, "tracing", 0))


def _abstract(tree):
    return jax.tree_util.tree_map(
        lambda a: (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape")
        else a, tree)


class StaticFunction:
    """Callable wrapping a Layer (or plain function) with compile cache
    (reference StaticFunction + its ProgramCache)."""

    def __init__(self, function, input_spec=None, build_strategy=None,
                 property=False):
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        else:
            self._layer = getattr(function, "__self__", None)
            self._fn = function
        self._input_spec = input_spec
        self._cache: Dict[Any, Any] = {}
        functools.update_wrapper(self, self._fn)

    # -- pure fn construction ---------------------------------------------
    def _make_pure(self, training: bool):
        layer = self._layer
        fn = self._fn

        def pure(params, buffers, key, args):
            _tls.tracing = getattr(_tls, "tracing", 0) + 1
            try:
                with prandom.rng_guard(key):
                    if layer is not None:
                        from ..func import functional_call
                        out, new_buf = functional_call(
                            layer, params, buffers, *args, training=training)
                    else:
                        wrapped = jax.tree_util.tree_map(Tensor, args)
                        out = fn(*wrapped)
                        out = jax.tree_util.tree_map(
                            lambda t: t.data if isinstance(t, Tensor) else t,
                            out, is_leaf=lambda t: isinstance(t, Tensor))
                        new_buf = {}
                return out, new_buf
            finally:
                _tls.tracing -= 1
        return pure

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._fn(*args, **kwargs) if self._layer is None else \
                self._layer(*args, **kwargs)
        layer = self._layer
        training = layer.training if layer is not None else False
        arg_arrays = tuple(
            a.data if isinstance(a, Tensor) else jnp.asarray(a)
            for a in args)
        if layer is not None:
            params, buffers = functional_state(layer)
        else:
            params, buffers = {}, {}
        cache_key = (training, _abstract(arg_arrays))
        entry = self._cache.get(cache_key)
        if entry is None:
            pure = self._make_pure(training)
            jitted = jax.jit(pure)
            entry = self._cache[cache_key] = jitted
        jitted = entry

        key = prandom.next_key()
        param_names = list(params)
        buf_names = list(buffers)

        # participate in the eager tape: params are differentiable leaves
        def tape_fn(*flat):
            p = dict(zip(param_names, flat[:len(param_names)]))
            b = dict(zip(buf_names,
                         flat[len(param_names):len(param_names) +
                              len(buf_names)]))
            in_args = flat[len(param_names) + len(buf_names):]
            out, new_buf = jitted(p, b, key, tuple(in_args))
            flat_out, treedef = jax.tree_util.tree_flatten(out)
            self._last_treedef = treedef
            self._n_out = len(flat_out)
            return tuple(flat_out) + tuple(new_buf[n] for n in buf_names
                                           if n in new_buf)

        param_tensors = [p for _, p in layer.named_parameters()] \
            if layer is not None else []
        buffer_tensors = [b for _, b in layer.named_buffers()
                          if b is not None] if layer is not None else []
        flat_in = [*param_tensors, *buffer_tensors,
                   *[a if isinstance(a, Tensor) else Tensor(a)
                     for a in args]]
        result = tape_apply(tape_fn, *flat_in, name="to_static")
        result = result if isinstance(result, tuple) else (result,)
        n_out = self._n_out
        outs = result[:n_out]
        new_bufs = result[n_out:]
        # write back mutated buffers (BatchNorm stats) eagerly
        live_buf = [b for _, b in layer.named_buffers()
                    if b is not None] if layer is not None else []
        for t, nb in zip(live_buf, new_bufs):
            t._data = nb.data
        out_tree = jax.tree_util.tree_unflatten(self._last_treedef, outs)
        return out_tree

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)

    def concrete_program(self, *args):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static parity."""
    def decorate(fn):
        return StaticFunction(fn, input_spec=input_spec,
                              build_strategy=build_strategy)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


# --------------------------------------------------------------------------
# save / load: StableHLO export for inference + state dict
# --------------------------------------------------------------------------

def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity: exports (a) state dict and (b) a serialized
    compiled inference function (StableHLO via jax.export) — the analogue
    of save_inference_model's Program + params (fluid/io.py:1199)."""
    from jax import export as jexport

    if isinstance(layer, StaticFunction):
        layer = layer._layer
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer or its to_static wrapper")
    if input_spec is None:
        raise ValueError(
            "jit.save needs input_spec=[InputSpec(shape, dtype), ...] or "
            "example tensors to trace the export")

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    params, buffers = functional_state(layer)
    was_training = layer.training
    layer.eval()
    try:
        def infer_fn(params, buffers, *args):
            from ..func import functional_call
            with prandom.rng_guard(jax.random.key(0)):
                out, _ = functional_call(layer, params, buffers, *args,
                                         training=False)
            return out

        shaped = []
        for spec in input_spec:
            if isinstance(spec, Tensor):
                shaped.append(
                    jax.ShapeDtypeStruct(tuple(spec.data.shape),
                                         spec.data.dtype))
            elif hasattr(spec, "shape"):
                shape = tuple(1 if s is None or s == -1 else int(s)
                              for s in spec.shape)
                dtype = getattr(spec, "dtype", None) or jnp.float32
                from ..core.dtype import convert_dtype
                shaped.append(jax.ShapeDtypeStruct(
                    shape, convert_dtype(dtype) or jnp.float32))
            else:
                shaped.append(spec)

        exported = jexport.export(jax.jit(infer_fn))(
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
            *shaped)
        blob = exported.serialize()
    finally:
        layer.train() if was_training else layer.eval()

    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    state = {"params": {k: np.asarray(v) for k, v in params.items()},
             "buffers": {k: np.asarray(v) for k, v in buffers.items()}}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)


class TranslatedLayer(Layer):
    """Inference layer over a deserialized export (reference
    TranslatedLayer from jit.load)."""

    def __init__(self, exported, params, buffers):
        super().__init__()
        self._exported = exported
        self._params = {k: jnp.asarray(v) for k, v in params.items()}
        self._buffers_arr = {k: jnp.asarray(v) for k, v in buffers.items()}

    def forward(self, *args):
        arrs = tuple(a.data if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in args)
        out = self._exported.call(self._params, self._buffers_arr, *arrs)
        return jax.tree_util.tree_map(Tensor, out)


def load(path, **configs):
    from jax import export as jexport
    with open(path + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    return TranslatedLayer(exported, state["params"], state["buffers"])
