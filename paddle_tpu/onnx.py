"""paddle.onnx (reference python/paddle/onnx/__init__.py — `export`
backed by the paddle2onnx converter package).

The TPU-native portable export is StableHLO (`paddle.jit.save`), which
any PJRT/OpenXLA runtime can load; ONNX serialization additionally
needs the `onnx` package, which this image does not ship, so export()
gates on it the way the reference gates on paddle2onnx.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "paddle.onnx.export needs the 'onnx' package, which is not "
            "installed in this environment. Use paddle.jit.save(layer, "
            "path, input_spec=...) for the portable StableHLO export "
            "instead.") from e
    raise NotImplementedError(
        "ONNX graph conversion is not implemented; use paddle.jit.save "
        "for the StableHLO export.")
