"""Control-flow op surface.

Reference: paddle/fluid/operators/controlflow/ (conditional_block_op,
while_op, ...) exposed through fluid/layers/control_flow.py
(cond/while_loop/case/switch_case). TPU-native: eager calls with
concrete predicates run plain Python (the reference dygraph behavior);
under a trace (jit/to_static/compiled trainers) they lower to
lax.cond / lax.while_loop / lax.switch — XLA's structured control flow,
the whole reason data-dependent Python branching is banned inside
compiled programs.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _arr(x):
    return x.data if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    return isinstance(_arr(x), jax.core.Tracer)


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: _arr(x), tree, is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a) if not isinstance(a, Tensor) else a, tree)


def cond(pred, true_fn: Callable = None, false_fn: Callable = None,
         name=None, return_names=None):
    """reference layers/control_flow cond (conditional_block_op). Both
    branches must return matching structures (same rule as the
    reference's static mode)."""
    p = _arr(pred)
    if not _is_traced(p):
        return true_fn() if bool(p) else false_fn()
    out = jax.lax.cond(
        jnp.asarray(p, bool).reshape(()),
        lambda _: _unwrap_tree(true_fn()),
        lambda _: _unwrap_tree(false_fn()),
        operand=None)
    return _wrap_tree(out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars,
               is_test=False, name=None):
    """reference layers/control_flow while_loop (while_op). loop_vars is
    a list/tuple; body must keep shapes/dtypes fixed (XLA semantics —
    the reference's LoD growth tricks map to pre-allocated buffers)."""
    loop_vars = list(loop_vars)
    traced = any(_is_traced(v) for v in
                 jax.tree_util.tree_leaves(_unwrap_tree(loop_vars)))
    if not traced:
        while bool(_arr(cond_fn(*loop_vars))):
            out = body_fn(*loop_vars)
            loop_vars = list(out) if isinstance(out, (list, tuple)) \
                else [out]
        return loop_vars

    def c(vs):
        return jnp.asarray(_arr(cond_fn(*_wrap_tree(list(vs)))),
                           bool).reshape(())

    def b(vs):
        out = body_fn(*_wrap_tree(list(vs)))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return tuple(_unwrap_tree(out))

    res = jax.lax.while_loop(c, b, tuple(_unwrap_tree(loop_vars)))
    return [t for t in _wrap_tree(list(res))]


def case(pred_fn_pairs: Sequence[Tuple], default: Optional[Callable] = None,
         name=None):
    """reference layers/control_flow case: first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must not be empty")
    preds = [p for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is None:
        default = fns[-1]
    if not any(_is_traced(p) for p in preds):
        for p, f in pred_fn_pairs:
            if bool(_arr(p)):
                return f()
        return default()
    # traced: nested conds, first-match semantics
    def build(i):
        if i == len(fns):
            return default()
        return cond(preds[i], fns[i], lambda: build(i + 1))
    return build(0)


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name=None):
    """reference layers/control_flow switch_case -> lax.switch."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns)) \
            if not isinstance(branch_fns[0], (tuple, list)) \
            else sorted((int(k), v) for k, v in branch_fns)
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    idx = _arr(branch_index)
    # reference semantics: with default=None the LAST branch is the
    # default — identical in eager and traced modes
    if default is None:
        default = fns[-1]
    if not _is_traced(idx):
        i = int(idx)
        for k, f in items:
            if k == i:
                return f()
        return default()
    # map branch_index -> dense position; unmatched -> default (last)
    table = jnp.asarray(keys, jnp.int32)
    pos = jnp.argmax(table == jnp.asarray(idx, jnp.int32))
    matched = jnp.any(table == jnp.asarray(idx, jnp.int32))
    dense = [lambda _, f=f: _unwrap_tree(f()) for f in fns]
    dense.append(lambda _: _unwrap_tree(default()))
    sel = jnp.where(matched, pos, len(fns))
    return _wrap_tree(jax.lax.switch(sel, dense, None))
