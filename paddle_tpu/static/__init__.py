"""paddle.static facade (reference python/paddle/static/).

There is no separate static-graph engine — XLA compiles traced programs
(paddle_tpu.jit). This module keeps the parity surface: InputSpec for
export signatures and thin aliases for the most-used static helpers.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..core.dtype import convert_dtype

from . import control_flow as _cf  # noqa: E402
from .control_flow import case, cond, switch_case, while_loop  # noqa: F401
from .program import (  # noqa: F401
    Executor, InferenceProgram, Program, Variable, default_main_program,
    default_startup_program, disable_static, enable_static,
    in_static_mode, load_inference_model, program_guard,
    save_inference_model)
from .helpers import *  # noqa: F401,F403,E402
from .helpers import __all__ as _helpers_all
from ..extension import py_func  # noqa: F401,E402
from .. import amp  # noqa: F401,E402  (paddle.static.amp surface)


def __getattr__(name):
    # static.nn imports functional layers -> lazy to avoid the
    # nn-package import cycle at paddle_tpu.static import time
    if name == "nn":
        import importlib
        mod = importlib.import_module(".nn", __name__)
        globals()["nn"] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu.static' has no attribute "
                         f"{name!r}")


__all__ = ["InputSpec", "data", "cond", "while_loop", "case",
           "switch_case", "nn", "Executor", "Program", "Variable",
           "program_guard", "default_main_program",
           "default_startup_program", "enable_static", "disable_static",
           "in_static_mode", "save_inference_model",
           "load_inference_model", "InferenceProgram", "py_func",
           "amp"] + list(_helpers_all)


class InputSpec:
    """reference python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.data.shape), tensor.data.dtype, name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data parity. In static mode (enable_static) this
    declares a symbolic graph input on the default Program; otherwise it
    returns an InputSpec (trace-export signature use, e.g. jit.save)."""
    if in_static_mode():
        from .program import record_data
        return record_data(name, shape, convert_dtype(dtype))
    return InputSpec(shape, dtype, name)
