"""paddle.static.nn — static-graph layer helpers (reference
python/paddle/static/nn/__init__.py, impls in fluid/layers/nn.py).

The reference helpers append ops + parameters to the default Program via
LayerHelper; here each call creates its Parameters eagerly (they are
captured by the recorded graph) and applies the functional op, which
records onto the Program in static mode. Same one-call-one-layer
contract as the reference.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Parameter, Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import ParamAttr
from .control_flow import case, cond, switch_case, while_loop  # noqa: F401
from .helpers import create_parameter  # noqa: F401
from ..extension import py_func  # noqa: F401

__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "case",
    "cond", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "create_parameter", "crf_decoding", "data_norm", "deform_conv2d",
    "group_norm", "instance_norm", "layer_norm", "multi_box_head", "nce",
    "prelu", "py_func", "row_conv", "spectral_norm", "switch_case",
    "while_loop", "sparse_embedding",
]


def _shape(x):
    if isinstance(x, Tensor):
        return tuple(x.data.shape)
    return tuple(x.shape)


def _dtype(x):
    if isinstance(x, Tensor):
        return x.data.dtype
    return x.dtype


def _make_param(shape, dtype, attr, is_bias=False, default_init=None):
    # single param factory — helpers.create_parameter owns the
    # attr -> initializer -> Parameter logic
    return create_parameter(shape, dtype, attr=attr, is_bias=is_bias,
                            default_initializer=default_init)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference fluid/layers/nn.py fc: flatten trailing dims, matmul,
    bias, optional activation. Accepts a list of inputs (summed)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = None
    for xi in xs:
        shp = _shape(xi)
        in_f = int(np.prod(shp[num_flatten_dims:]))
        w = _make_param([in_f, size], _dtype(xi), weight_attr)
        flat = F.linear(
            xi.reshape((*shp[:num_flatten_dims], in_f))
            if len(shp) != 2 or num_flatten_dims != 1 else xi, w)
        out = flat if out is None else out + flat
    b = _make_param([size], _dtype(xs[0]), bias_attr, is_bias=True)
    if b is not None:
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """reference fluid/input.py embedding (lookup_table_v2)."""
    w = _make_param(list(size), dtype, param_attr,
                    default_init=I.Normal(0.0, 1.0 / math.sqrt(size[1])))
    return F.embedding(input, w, padding_idx=padding_idx,
                       sparse=is_sparse)


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype="float32"):
    """reference fluid/contrib sparse_embedding: the PS large-vocab
    table; here = embedding with the SelectedRows sparse-grad path."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def _conv_nd(x, num_filters, filter_size, nd, stride, padding, dilation,
             groups, param_attr, bias_attr, act, transpose=False,
             output_size=None):
    shp = _shape(x)
    cin = shp[1]
    ks = [filter_size] * nd if isinstance(filter_size, int) \
        else list(filter_size)
    if transpose:
        wshape = [cin, num_filters // (groups or 1)] + ks
    else:
        wshape = [num_filters, cin // (groups or 1)] + ks
    fan_in = (cin // (groups or 1)) * int(np.prod(ks))
    bound = math.sqrt(1.0 / max(fan_in, 1))
    w = _make_param(wshape, _dtype(x), param_attr,
                    default_init=I.Uniform(-bound, bound))
    b = _make_param([num_filters], _dtype(x), bias_attr, is_bias=True)
    if transpose:
        fn = {2: F.conv2d_transpose, 3: F.conv3d_transpose}[nd]
        out = fn(x, w, b, stride=stride, padding=padding,
                 groups=groups or 1, output_size=output_size)
    else:
        fn = {2: F.conv2d, 3: F.conv3d}[nd]
        out = fn(x, w, b, stride=stride, padding=padding,
                 dilation=dilation, groups=groups or 1)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None,
           data_format="NCHW"):
    return _conv_nd(input, num_filters, filter_size, 2, stride, padding,
                    dilation, groups, param_attr, bias_attr, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    return _conv_nd(input, num_filters, filter_size, 3, stride, padding,
                    dilation, groups, param_attr, bias_attr, act)


def conv2d_transpose(input, num_filters, output_size=None,
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=None, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCHW"):
    if filter_size is None:
        raise ValueError("conv2d_transpose: filter_size is required "
                         "(output_size-only inference not supported)")
    return _conv_nd(input, num_filters, filter_size, 2, stride, padding,
                    dilation, groups, param_attr, bias_attr, act,
                    transpose=True, output_size=output_size)


def conv3d_transpose(input, num_filters, output_size=None,
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=None, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCDHW"):
    if filter_size is None:
        raise ValueError("conv3d_transpose: filter_size is required")
    return _conv_nd(input, num_filters, filter_size, 3, stride, padding,
                    dilation, groups, param_attr, bias_attr, act,
                    transpose=True, output_size=output_size)


def batch_norm(input, act=None, is_test=False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """reference batch_norm_op.cc. moving_mean/moving_variance are
    persistable non-trainable variables: the training path records their
    momentum update (written back after every run — reference
    MomentumUpdate in batch_norm_op), and the is_test/use_global_stats
    path normalizes with THEM, not fresh (0,1) constants."""
    C = _shape(input)[1]
    dt = _dtype(input)
    w = _make_param([C], dt, param_attr, default_init=I.Constant(1.0))
    b = _make_param([C], dt, bias_attr, is_bias=True)
    training = not (is_test or use_global_stats)
    rm = Tensor(jnp.zeros((C,), dt), name=moving_mean_name,
                persistable=True)
    rv = Tensor(jnp.ones((C,), dt), name=moving_variance_name,
                persistable=True)
    rm.stop_gradient = rv.stop_gradient = True
    # batch-vs-moving statistics selected by a RUNTIME flag capture, not
    # a trace-time constant: Program.clone(for_test=True) zeroes every
    # marked flag at run time, so the cloned graph serves inference with
    # the trained moving statistics (reference test-program semantics)
    fl = Tensor(jnp.asarray(1.0 if training else 0.0, jnp.float32))
    fl.stop_gradient = True
    fl._bn_train_flag = True

    # routed through apply (not F.batch_norm) so static mode records it.
    # attr=False params run as affine identity (reference allows it)
    def fn(a, ww, bb, mm, vv, flg):
        ax = (1, -1) + (1,) * (a.ndim - 2)
        red = (0,) + tuple(range(2, a.ndim))

        def batch_stats(_):
            mu_b = a.mean(axis=red)
            return mu_b, ((a - mu_b.reshape(ax)) ** 2).mean(axis=red)

        # lax.cond, not where: inference runs must not pay the batch
        # reductions they discard
        mu, var = jax.lax.cond(flg > 0.5, batch_stats,
                               lambda _: (mm, vv), None)
        out = (a - mu.reshape(ax)) * jax.lax.rsqrt(
            var.reshape(ax) + epsilon)
        out = out * ww.reshape(ax) + bb.reshape(ax)
        new_mm = momentum * mm + (1.0 - momentum) * mu
        new_vv = momentum * vv + (1.0 - momentum) * var
        return out, new_mm, new_vv

    out, new_mm, new_vv = apply(
        fn, input,
        w if w is not None else Tensor(jnp.ones((C,), dt)),
        b if b is not None else Tensor(jnp.zeros((C,), dt)),
        rm, rv, fl, name="batch_norm")
    if training:
        from .program import Variable, default_main_program, in_static_mode
        if in_static_mode() and isinstance(new_mm, Variable):
            # the Executor fetches these alongside every run and writes
            # them back into rm/rv (the reference's in-place moving
            # average ops)
            default_main_program()._updates += [(rm, new_mm), (rv, new_vv)]
        else:  # eager: write back immediately
            rm._data = new_mm.data
            rv._data = new_vv.data
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shp = _shape(input)
    norm_shape = shp[begin_norm_axis:]
    dt = _dtype(input)
    n = int(np.prod(norm_shape))
    w = _make_param([n], dt, param_attr,
                    default_init=I.Constant(1.0)) if scale else None
    b = _make_param([n], dt, bias_attr, is_bias=True) if shift else None

    def fn(a, *wb):
        # unpack by which params actually exist (attr=False drops one)
        have_w = w is not None
        have_b = b is not None
        ww = wb[0] if have_w else None
        bb = wb[1 if have_w else 0] if have_b else None
        ax = tuple(range(begin_norm_axis, a.ndim))
        mu = a.mean(axis=ax, keepdims=True)
        var = ((a - mu) ** 2).mean(axis=ax, keepdims=True)
        out = (a - mu) * jax.lax.rsqrt(var + epsilon)
        tail = a.shape[begin_norm_axis:]
        if ww is not None:
            out = out * ww.reshape(tail)
        if bb is not None:
            out = out + bb.reshape(tail)
        return out

    args = [a for a in (w, b) if a is not None]
    out = apply(fn, input, *args, name="layer_norm")
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    C = _shape(input)[1]
    dt = _dtype(input)
    w = _make_param([C], dt, param_attr, default_init=I.Constant(1.0))
    b = _make_param([C], dt, bias_attr, is_bias=True)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    C = _shape(input)[1]
    dt = _dtype(input)
    w = _make_param([C], dt, param_attr, default_init=I.Constant(1.0))
    b = _make_param([C], dt, bias_attr, is_bias=True)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_0=0.9999999):
    """reference data_norm_op.cc (CTR feature normalization): normalize
    by accumulated batch_sum / batch_size statistics, which train as
    parameters (no beta/gamma)."""
    C = _shape(input)[-1]
    dt = _dtype(input)
    batch_size = _make_param([C], dt, param_attr,
                             default_init=I.Constant(1e4))
    batch_sum = _make_param([C], dt, param_attr,
                            default_init=I.Constant(0.0))
    batch_square = _make_param([C], dt, param_attr,
                               default_init=I.Constant(1e4))
    if batch_size is None:  # attr=False: fixed identity statistics
        batch_size = Tensor(jnp.full((C,), 1e4, dt))
        batch_sum = Tensor(jnp.zeros((C,), dt))
        batch_square = Tensor(jnp.full((C,), 1e4, dt))

    def fn(a, n, s, sq):
        mean = s / n
        scale = jnp.sqrt(n / jnp.maximum(sq, epsilon))
        out = (a - mean) * scale
        return out

    out = apply(fn, input, batch_size, batch_sum, batch_square,
                name="data_norm")
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    """reference prelu_op.cc: mode all (one alpha) / channel / element."""
    shp = _shape(x)
    dt = _dtype(x)
    if mode == "all":
        ashape = [1]
    elif mode == "channel":
        ashape = [shp[1]]
    elif mode == "element":
        ashape = list(shp[1:])
    else:
        raise ValueError(f"prelu mode {mode!r}")
    alpha = _make_param(ashape, dt, param_attr,
                        default_init=I.Constant(0.25))
    if alpha is None:  # attr=False: the reference's default slope
        alpha = Tensor(jnp.full(ashape, 0.25, dt))

    def fn(a, al):
        if mode == "channel":
            al = al.reshape((1, -1) + (1,) * (a.ndim - 2))
        elif mode == "element":
            al = al.reshape((1,) + a.shape[1:])
        return jnp.where(a > 0, a, al * a)

    return apply(fn, x, alpha, name="prelu")


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference row_conv_op.cc (lookahead conv for streaming ASR):
    out[t] = sum_{i=0..k} in[t+i] * w[i] over [B, T, D]."""
    D = _shape(input)[-1]
    k = future_context_size
    w = _make_param([k + 1, D], _dtype(input), param_attr)
    if w is None:
        raise ValueError("row_conv requires a weight parameter "
                         "(param_attr must not be False)")

    def fn(a, ww):
        pads = [(0, 0)] * a.ndim
        pads[-2] = (0, k)
        ap = jnp.pad(a, pads)
        T = a.shape[-2]
        out = 0.0
        for i in range(k + 1):
            out = out + ap[..., i:i + T, :] * ww[i]
        return out

    out = apply(fn, input, w, name="row_conv")
    if act:
        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference spectral_norm_op.cc: w / sigma_max(w) estimated by
    `power_iters` rounds of power iteration from fixed unit vectors
    (deterministic under jit, like the persisted u/v of the reference)."""
    def fn(w):
        wm = jnp.moveaxis(w, dim, 0)
        h = wm.shape[0]
        mat = wm.reshape(h, -1).astype(jnp.float32)
        u = jnp.ones((h,), jnp.float32) / math.sqrt(h)
        v = None
        for _ in range(max(power_iters, 1)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ mat @ v
        return (w / sigma.astype(w.dtype))

    return apply(fn, weight, name="spectral_norm")


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference bilinear_tensor_product_op.cc: out_k = x^T W_k y + b."""
    dx = _shape(x)[-1]
    dy = _shape(y)[-1]
    dt = _dtype(x)
    w = _make_param([size, dx, dy], dt, param_attr)
    if w is None:
        raise ValueError("bilinear_tensor_product requires a weight "
                         "parameter (param_attr must not be False)")
    b = _make_param([1, size], dt, bias_attr, is_bias=True)

    def fn(xa, ya, wa, *rest):
        out = jnp.einsum("bi,kij,bj->bk", xa, wa, ya)
        if rest:
            out = out + rest[0]
        return out

    args = [x, y, w] + ([b] if b is not None else [])
    out = apply(fn, *args, name="bilinear_tensor_product")
    if act:
        out = getattr(F, act)(out)
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nce_op.cc), uniform
    negative sampling. Returns per-sample loss [N, 1]."""
    from ..core import random as prandom

    D = _shape(input)[-1]
    dt = _dtype(input)
    w = _make_param([num_total_classes, D], dt, param_attr)
    if w is None:
        raise ValueError("nce requires a weight parameter "
                         "(param_attr must not be False)")
    b = _make_param([num_total_classes], dt, bias_attr, is_bias=True)
    k = num_neg_samples

    # Negatives must be RESAMPLED every execution (the reference nce_op
    # draws per iteration); a bare PRNGKey(seed) inside fn would bake
    # one fixed sample set into the recorded graph forever. The base key
    # is drawn once (paddle convention: seed=0 means "random"), and a
    # captured per-call-site iteration counter is folded in; the
    # Executor bumps every marked counter after each run, and captures
    # are runtime arguments of the compiled step, so the fold_in sees
    # the new value without a retrace.
    base_key = jax.random.PRNGKey(seed) if seed else prandom.next_key()
    it = Tensor(jnp.zeros((), jnp.int32))
    it.stop_gradient = True
    it._iteration_counter = True

    def fn(xa, lab, wa, ba, it_no):
        N = xa.shape[0]
        lab = lab.reshape(-1).astype(jnp.int32)
        key = jax.random.fold_in(base_key, it_no)
        neg = jax.random.randint(key, (N, k), 0, num_total_classes)
        pos_logit = jnp.einsum("nd,nd->n", xa, wa[lab]) + ba[lab]
        neg_logit = jnp.einsum("nd,nkd->nk", xa, wa[neg]) + ba[neg]
        # NCE with uniform noise: P_noise = 1/V; logit shift log(k*Pn)
        shift = jnp.log(jnp.float32(k) / num_total_classes)
        pos = jax.nn.softplus(-(pos_logit - shift))
        negs = jax.nn.softplus(neg_logit - shift).sum(axis=1)
        return (pos + negs).reshape(-1, 1)

    return apply(fn, input, label, w,
                 b if b is not None else
                 Tensor(jnp.zeros((num_total_classes,), dt)),
                 it, name="nce")


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """reference crf_decoding_op.cc: viterbi best path. `transition`
    may be passed directly (the linear_chain_crf parameter, including
    the reference's start/stop rows at [0]/[1]); otherwise one is
    created. With `label`, returns the per-step correctness mask like
    the reference."""
    from ..text.decoding import viterbi_decode
    T = _shape(input)[-1]
    if transition is None:
        transition = _make_param([T + 2, T], _dtype(input), param_attr)

    # strip the start/stop rows the linear_chain_crf parameter carries
    trans_body = apply(lambda t: t[2:], transition, name="crf_trans")
    _, path = viterbi_decode(input, trans_body,
                             lengths=length, include_bos_eos_tag=False)
    if label is not None:
        eq = apply(lambda a, b: (a == b.reshape(a.shape)).astype(
            jnp.int64), path, label, name="crf_correct")
        return eq
    return path


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2),
                   flip=True, clip=False, kernel_size=1, pad=0,
                   stride=1, name=None, min_max_aspect_ratios_order=False):
    """SSD detection head (reference detection/multi_box_head in
    fluid/layers/detection.py): per-feature-map conv predictors for
    location + confidence, plus prior boxes. Returns
    (mbox_locs [N, P, 4], mbox_confs [N, P, C], boxes [P, 4],
    variances [P, 4])."""
    n_maps = len(inputs)
    if min_sizes is None:
        # the reference's ratio schedule
        min_ratio, max_ratio = min_ratio or 20, max_ratio or 90
        step = int(math.floor((max_ratio - min_ratio) /
                              max(n_maps - 2, 1)))
        min_sizes, max_sizes = [], []
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_maps - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_maps - 1]

    locs, confs, priors, pvars = [], [], [], []
    img_h = _shape(image)[2]
    img_w = _shape(image)[3]
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        mn = min_sizes[i] if not isinstance(min_sizes[i],
                                            (list, tuple)) \
            else min_sizes[i]
        mn_list = [mn] if not isinstance(mn, (list, tuple)) else list(mn)
        mx = None
        if max_sizes is not None:
            mx = max_sizes[i]
            mx = [mx] if not isinstance(mx, (list, tuple)) else list(mx)
        fh, fw = _shape(feat)[2], _shape(feat)[3]
        from ..vision.ops import prior_box as _prior
        # explicit strides (standard SSD configs) override the
        # image/feature ratio; step_w/step_h pin both axes the same way
        step_i = None
        if steps is not None:
            step_i = steps[i] if isinstance(steps, (list, tuple)) \
                else steps
        elif step_w is not None or step_h is not None:
            step_i = step_w if step_w is not None else step_h
        boxes = _prior(fh, fw, img_h, img_w, mn_list,
                       max_sizes=mx or (), aspect_ratios=ar, flip=flip,
                       clip=clip, offset=offset, step=step_i)
        n_priors_per_cell = boxes.shape[2]
        boxes = boxes.reshape([-1, 4])
        priors.append(boxes)
        pvars.append(Tensor(jnp.tile(
            jnp.asarray(variance, jnp.float32)[None, :],
            (boxes.shape[0], 1))))
        loc = conv2d(feat, n_priors_per_cell * 4, kernel_size,
                     stride=stride, padding=pad)
        conf = conv2d(feat, n_priors_per_cell * num_classes, kernel_size,
                      stride=stride, padding=pad)

        def nchw_to_flat(t, last):
            n = _shape(t)[0]
            return apply(
                lambda a: jnp.moveaxis(a, 1, -1).reshape(
                    a.shape[0], -1, last), t, name="transpose_flatten")

        locs.append(nchw_to_flat(loc, 4))
        confs.append(nchw_to_flat(conf, num_classes))

    from ..tensor import concat
    return (concat(locs, axis=1), concat(confs, axis=1),
            concat(priors, axis=0), concat(pvars, axis=0))


def deform_conv2d(input, offset, mask, num_filters, filter_size,
                  stride=1, padding=0, dilation=1, groups=1,
                  deformable_groups=1, im2col_step=1, param_attr=None,
                  bias_attr=None, modulated=True, name=None):
    """static.nn.deform_conv2d (reference static/nn/common.py): creates
    the filter parameter and applies the deformable conv op."""
    from ..vision.ops import deform_conv2d as _dcn
    cin = _shape(input)[1]
    ks = [filter_size] * 2 if isinstance(filter_size, int) \
        else list(filter_size)
    fan_in = (cin // groups) * int(np.prod(ks))
    bound = math.sqrt(1.0 / max(fan_in, 1))
    w = _make_param([num_filters, cin // groups] + ks, _dtype(input),
                    param_attr, default_init=I.Uniform(-bound, bound))
    b = _make_param([num_filters], _dtype(input), bias_attr,
                    is_bias=True)
    return _dcn(input, offset, w, bias=b, stride=stride, padding=padding,
                dilation=dilation, deformable_groups=deformable_groups,
                groups=groups, mask=mask if modulated else None)
