"""paddle.static parity helpers (reference python/paddle/static/__init__.py
surface: scopes, places, strategies, program save/load, debug ops).

The reference backs these with the C++ Scope/ParallelExecutor machinery;
here programs are traced graphs compiled by XLA, so the classes keep the
API shape while the compiled path does the work (SURVEY.md §7 map).
"""
from __future__ import annotations

import contextlib
import os
import pickle
import re
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.tensor import Parameter, Tensor
from ..nn.layer_base import ParamAttr
from .program import (Executor, Program, Variable, default_main_program,
                      record_gradients)

__all__ = [
    "Scope", "global_scope", "scope_guard", "device_guard", "name_scope",
    "cpu_places", "cuda_places", "xpu_places", "tpu_places",
    "create_parameter", "create_global_var", "Print", "accuracy", "auc",
    "append_backward", "gradients", "BuildStrategy", "ExecutionStrategy",
    "CompiledProgram", "ParallelExecutor", "WeightNormParamAttr",
    "save", "load", "save_vars", "load_vars", "save_to_file",
    "load_from_file", "set_program_state", "load_program_state",
    "serialize_program", "deserialize_program", "serialize_persistables",
    "deserialize_persistables",
]


# ---------------------------------------------------------------------------
# scopes
# ---------------------------------------------------------------------------
class _ScopeVar:
    """Variable slot in a Scope (reference framework::Variable): holds a
    numpy value accessed through get_tensor()."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def get_tensor(self):
        return self

    # tensor-protocol surface used by reference idioms
    def set(self, value, place=None):
        self._value = np.asarray(value)

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype else a

    def shape(self):
        return tuple(np.asarray(self._value).shape)


class Scope:
    """Hierarchical name->var map (reference framework/scope.h:52)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, _ScopeVar] = {}
        self._parent = parent
        self._kids: List["Scope"] = []

    def var(self, name):
        if name not in self._vars:
            self._vars[name] = _ScopeVar(name)
        return self._vars[name]

    def find_var(self, name):
        if name in self._vars:
            return self._vars[name]
        return self._parent.find_var(name) if self._parent else None

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    def local_var_names(self):
        return list(self._vars)


_GLOBAL_SCOPE = Scope()
_SCOPE_STACK = [_GLOBAL_SCOPE]


def global_scope() -> Scope:
    return _SCOPE_STACK[-1]


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _SCOPE_STACK.append(scope)
    try:
        yield
    finally:
        _SCOPE_STACK.pop()


# ---------------------------------------------------------------------------
# places / guards
# ---------------------------------------------------------------------------
def cpu_places(device_count=None):
    from ..device import CPUPlace
    if device_count is None:
        device_count = int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(device_count)]


def cuda_places(device_ids=None):
    """No CUDA on this build — kept for API parity; returns []. Use
    tpu_places()."""
    return []


def xpu_places(device_ids=None):
    return []


def tpu_places(device_ids=None):
    from ..device import TPUPlace
    import jax
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if device_ids is not None:
        devs = [devs[i] for i in device_ids]
    return [TPUPlace(d.id) for d in devs]


@contextlib.contextmanager
def device_guard(device=None):
    """reference fluid/framework.py:5761 device_guard — pins ops to a
    device in the pipeline pass. The TPU pipeline assigns stages by
    mesh sharding (distributed/pipeline.py), so this only annotates."""
    yield


_NAME_SCOPE: List[str] = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """reference framework.py name_scope: prefixes recorded op names."""
    _NAME_SCOPE.append(prefix or "scope")
    try:
        yield
    finally:
        _NAME_SCOPE.pop()


def current_name_scope() -> str:
    return "/".join(_NAME_SCOPE)


# ---------------------------------------------------------------------------
# parameters / vars
# ---------------------------------------------------------------------------
def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference fluid/layers/tensor.py create_parameter: a free-standing
    trainable Parameter (Xavier init by default, zeros for bias)."""
    from ..core.dtype import convert_dtype
    from ..nn import initializer as I

    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    init = (attr.initializer if attr is not None and attr.initializer
            else default_initializer)
    if init is None:
        gw, gb = I.get_global_initializer()
        init = (gb or I.Constant(0.0)) if is_bias else \
            (gw or I.XavierUniform())
    data = init(tuple(int(s) for s in shape), convert_dtype(dtype))
    p = Parameter(data)
    if name or (attr is not None and attr.name):
        p.name = name or attr.name
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference create_global_var: a persistable constant-initialized
    variable. Non-trainable Tensor here (captured by recorded graphs)."""
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    t = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                        dtype=convert_dtype(dtype)))
    t.stop_gradient = True
    if name:
        t.name = name
    return t


# ---------------------------------------------------------------------------
# debug / metrics ops
# ---------------------------------------------------------------------------
def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """reference operators/print_op.cc: pass-through that prints its
    input. Inside a compiled graph this lowers to jax.debug.print."""
    import jax
    from ..core.autograd import apply

    msg = message or ""

    def fn(a):
        jax.debug.print(msg + " {x}", x=a)
        return a

    return apply(fn, input, name="print")


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric.metrics import accuracy as _acc
    return _acc(input, label, k=k, correct=correct, total=total)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC (reference operators/metrics/auc_op.cc): threshold-
    bucketed trapezoid over the positive-class score input[:, 1]."""
    import jax.numpy as jnp
    from ..core.autograd import apply

    def fn(x, lab):
        pos = x[:, 1] if x.ndim == 2 and x.shape[1] == 2 else \
            x.reshape(x.shape[0], -1)[:, -1]
        lab = lab.reshape(-1).astype(jnp.float32)
        idx = jnp.clip((pos * num_thresholds).astype(jnp.int32), 0,
                       num_thresholds)
        tp = jnp.zeros(num_thresholds + 1).at[idx].add(lab)
        fp = jnp.zeros(num_thresholds + 1).at[idx].add(1.0 - lab)
        # cumulative from the highest threshold down
        tp_c = jnp.cumsum(tp[::-1])
        fp_c = jnp.cumsum(fp[::-1])
        tpr = tp_c / jnp.maximum(tp_c[-1], 1.0)
        fpr = fp_c / jnp.maximum(fp_c[-1], 1.0)
        return jnp.trapezoid(tpr, fpr).astype(jnp.float32)

    return apply(fn, input, label, name="auc")


# ---------------------------------------------------------------------------
# autodiff
# ---------------------------------------------------------------------------
def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference fluid/backward.py:1337 append_backward — records grad
    computation for every trainable Parameter feeding `loss`; returns
    [(param, grad_variable)] pairs."""
    from .program import _collect
    if parameter_list is None:
        _, caps, _ = _collect([loss])
        parameter_list = [t for t in caps if isinstance(t, Parameter)
                          and t.trainable]
    no_grad = no_grad_set or set()
    parameter_list = [p for p in parameter_list
                      if getattr(p, "name", None) not in no_grad]
    if not parameter_list:
        return []
    grads = record_gradients([loss], parameter_list,
                             name="append_backward")
    return list(zip(parameter_list, grads))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference fluid/backward.py:1932 gradients — d(sum targets)/d
    inputs; inputs may be graph inputs, intermediates, or Parameters."""
    if target_gradients is not None:
        raise NotImplementedError(
            "target_gradients (custom output grads) is not supported; "
            "seed via a weighted sum of targets instead")
    return record_gradients(targets, inputs, name="gradients")


# ---------------------------------------------------------------------------
# strategies / compiled programs (legacy ParallelExecutor surface)
# ---------------------------------------------------------------------------
class BuildStrategy:
    """reference details/build_strategy.h knob bag. XLA's compile does
    fusion/memory planning, so the knobs are accepted and recorded; the
    ones with a TPU equivalent are honored by SpmdTrainer via
    DistributedStrategy."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_broadcast_ops = False
        self.fuse_all_optimizer_ops = False
        self.fuse_all_reduce_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_auto_fusion = False
        self.enable_inplace = False
        self.memory_optimize = None
        self.sync_batch_norm = False
        self.remove_unnecessary_lock = True
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """reference details/execution_strategy.h: scheduler knobs — the XLA
    step is a single executable, so these only shape the Python loop."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = True


class CompiledProgram:
    """reference compiler.py CompiledProgram: wraps a Program (+build
    strategy); Executor.run unwraps it. with_data_parallel keeps the
    chain-call shape — on TPU the dp dimension comes from the mesh
    (distributed.SpmdTrainer), not from graph cloning."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._places = None
        self._loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        return self


class ParallelExecutor:
    """Legacy reference parallel_executor.cc surface, delegating to the
    compiled Executor (GSPMD replaces the SSA-graph scheduler)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or default_main_program()
        self._exe = Executor()
        self._loss_name = loss_name

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


class WeightNormParamAttr(ParamAttr):
    """reference fluid/param_attr.py WeightNormParamAttr — marks a
    parameter for g·v/||v|| reparameterization; layers honor it through
    nn.utils.weight_norm applied to the owning layer."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         regularizer=regularizer, trainable=trainable,
                         do_model_average=do_model_average,
                         need_clip=need_clip)
        self.dim = dim


# ---------------------------------------------------------------------------
# program state save/load (reference static/io.py + fluid/io.py)
# ---------------------------------------------------------------------------
def _program_params(program) -> List[Parameter]:
    """Persistables of a program: trainable Parameters plus persistable
    non-trainable Tensors (batch_norm moving statistics), in graph
    collection order (deterministic for a given program structure)."""
    from .program import _collect
    seen, out = set(), []
    roots = []
    for n in program.nodes:
        roots.extend(n.outputs)
    if not roots:
        return []
    _, caps, _ = _collect(roots)
    for t in caps:
        if id(t) in seen:
            continue
        if isinstance(t, Parameter) or getattr(t, "persistable", False):
            seen.add(id(t))
            out.append(t)
    return out


_AUTO_NAME = re.compile(r"^generated_tensor_\d+$")


def _canonical_pairs(program) -> List[tuple]:
    """[(canonical_name, param)] in graph collection order. Auto-
    generated names (generated_tensor_N from the global tensor counter)
    depend on how many unnamed Tensors happened to be created first, so
    checkpoints keyed by them only load into a process that allocated
    tensors in the identical order; they are replaced by a per-program
    position index. Duplicates are NOT rejected here — callers raise
    over the subset they actually touch."""
    pairs = []
    for i, p in enumerate(_program_params(program)):
        name = p.name
        if name is None or _AUTO_NAME.match(name):
            name = f"_param_{i}"
        pairs.append((name, p))
    return pairs


def _reject_duplicates(pairs):
    seen = set()
    for name, _ in pairs:
        if name in seen:
            raise ValueError(
                f"duplicate parameter name {name!r} in program: saving "
                f"would silently drop one of them; give the parameters "
                f"distinct ParamAttr names")
        seen.add(name)
    return pairs


def _canonical_named_params(program) -> Dict[str, Parameter]:
    """name -> parameter with DETERMINISTIC names; raises on two
    persistables sharing an explicit name (a dict would silently keep
    one and drop the other)."""
    return dict(_reject_duplicates(_canonical_pairs(program)))


def _state_of(program) -> Dict[str, np.ndarray]:
    return {name: np.asarray(p.data)
            for name, p in _canonical_named_params(program).items()}


def save(program, model_path, protocol=4):
    """reference paddle.static.save: persist program parameters to
    `model_path + '.pdparams'` through the pluggable fs backend."""
    from ..framework.fs import open_for_write
    state = _state_of(program)
    with open_for_write(model_path + ".pdparams") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """reference paddle.static.load: restore parameters saved by save."""
    state = load_program_state(model_path, var_list=var_list)
    set_program_state(program, state)


def load_program_state(model_path, var_list=None):
    from ..framework.fs import open_for_read
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    with open_for_read(path) as f:
        state = pickle.load(f)
    if var_list is not None:
        names = {getattr(v, "name", v) for v in var_list}
        state = {k: v for k, v in state.items() if k in names}
    return state


def set_program_state(program, state_dict):
    import jax.numpy as jnp
    params = _canonical_named_params(program)
    missing = sorted(set(state_dict) - set(params))
    for name, p in params.items():
        if name not in state_dict and p.name in state_dict:
            # pre-canonical checkpoint keyed by the raw auto name:
            # accept it when the raw name still matches (same-process
            # legacy state) rather than silently leaving the parameter
            # at its init value
            name = p.name
            missing = [m for m in missing if m != name]
        if name in state_dict:
            a = np.asarray(state_dict[name])
            if tuple(a.shape) != tuple(p.data.shape):
                raise ValueError(
                    f"set_program_state: shape mismatch for {name}: "
                    f"{a.shape} vs {tuple(p.data.shape)}")
            p._data = jnp.asarray(a, dtype=p.data.dtype)
    if missing:
        import warnings
        warnings.warn(f"set_program_state: {len(missing)} entries had no "
                      f"matching parameter: {missing[:5]}...")


def _selected_named_params(program, vars=None, predicate=None):
    """(canonical_name, param) pairs filtered the save_vars/load_vars
    way. Canonical names (not raw auto-generated ones) key the files, so
    a fresh process with a shifted global tensor counter still matches;
    explicit `vars` filters match either spelling. Duplicate names are
    rejected only within the SELECTED subset — duplicates elsewhere in
    the program don't block saving an unrelated var."""
    items = _canonical_pairs(program)
    if vars is not None:
        names = {getattr(v, "name", v) for v in vars}
        items = [(n, p) for n, p in items
                 if n in names or p.name in names]
    if predicate is not None:
        items = [(n, p) for n, p in items if predicate(p)]
    return _reject_duplicates(items)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference fluid/io.py save_vars: one file per var (or a combined
    `filename`)."""
    program = main_program or default_main_program()
    items = _selected_named_params(program, vars, predicate)
    from ..framework.fs import open_for_write, get_fs
    get_fs(dirname).makedirs(dirname)
    if filename:
        with open_for_write(os.path.join(dirname, filename)) as f:
            pickle.dump({n: np.asarray(p.data) for n, p in items}, f)
    else:
        for n, p in items:
            with open_for_write(os.path.join(dirname, n)) as f:
                pickle.dump(np.asarray(p.data), f)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    items = _selected_named_params(program, vars, predicate)
    from ..framework.fs import open_for_read
    if filename:
        with open_for_read(os.path.join(dirname, filename)) as f:
            state = pickle.load(f)
        for n, p in items:
            if n in state:
                p._data = jnp.asarray(state[n], dtype=p.data.dtype)
    else:
        for n, p in items:
            with open_for_read(os.path.join(dirname, n)) as f:
                p._data = jnp.asarray(pickle.load(f),
                                      dtype=p.data.dtype)


def save_to_file(path, content: bytes):
    from ..framework.fs import open_for_write
    with open_for_write(path) as f:
        f.write(content)


def load_from_file(path) -> bytes:
    from ..framework.fs import open_for_read
    with open_for_read(path) as f:
        return f.read()


def serialize_persistables(feed_vars, fetch_vars) -> bytes:
    """reference static/io.py serialize_persistables: parameters of the
    program feeding fetch_vars, pickled."""
    from .program import _collect
    fetch_vars = [fetch_vars] if isinstance(fetch_vars, Variable) \
        else list(fetch_vars)
    _, caps, _ = _collect(fetch_vars)
    state = {t.name: np.asarray(t.data) for t in caps
             if isinstance(t, Parameter)}
    return pickle.dumps(state)


def deserialize_persistables(program, data: bytes, executor=None):
    set_program_state(program, pickle.loads(data))


def serialize_program(feed_vars, fetch_vars) -> bytes:
    """reference static/io.py serialize_program. The portable compiled
    form of a traced program is its StableHLO export — the same artifact
    save_inference_model writes (jit/api.py)."""
    import tempfile
    from .program import save_inference_model as _sim
    feed_vars = [feed_vars] if isinstance(feed_vars, Variable) \
        else list(feed_vars)
    fetch_vars = [fetch_vars] if isinstance(fetch_vars, Variable) \
        else list(fetch_vars)
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "prog")
        _sim(prefix, feed_vars, fetch_vars)
        payload = {}
        for fn in sorted(os.listdir(d)):
            with open(os.path.join(d, fn), "rb") as f:
                payload[fn] = f.read()
    return pickle.dumps(payload)


def deserialize_program(data: bytes):
    """Inverse of serialize_program: returns an InferenceProgram
    Executor.run can execute."""
    import tempfile
    from .program import load_inference_model as _lim
    payload = pickle.loads(data)
    with tempfile.TemporaryDirectory() as d:
        for fn, blob in payload.items():
            with open(os.path.join(d, fn), "wb") as f:
                f.write(blob)
        prefix = os.path.join(d, "prog")
        prog, _, _ = _lim(prefix)
        return prog
