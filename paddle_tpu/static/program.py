"""Static-graph surface: Program / Variable / Executor / program_guard.

Reference: python/paddle/fluid/framework.py (Program:4127, Variable:978,
program/unique-name guards), executor.py:475 (Executor.run with
feed/fetch), and the classic static workflow

    paddle.enable_static()
    x = paddle.static.data('x', [None, 4])
    loss = mean(net(x))
    sgd.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    exe.run(main_program, feed={'x': a}, fetch_list=[loss])

TPU-native design: there is no op-desc IR — with static mode enabled,
every op that flows through core.autograd.apply records a NODE (the op's
jax function + its symbolic/captured inputs) onto the default Program
instead of executing.  Executor.run topologically re-executes the
recorded graph as ONE jit-compiled function per (program, fetch, feed
shapes): parameters enter as arguments (not baked constants), so
optimizer updates — recorded by Optimizer.minimize on a symbolic loss —
run inside the same executable, exactly the fused train step the
ParallelExecutor analogue uses.  Shapes declared None are dynamic: the
graph re-traces per concrete feed shape.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["Variable", "Program", "Executor", "program_guard",
           "default_main_program", "default_startup_program",
           "enable_static", "disable_static", "in_static_mode",
           "save_inference_model", "load_inference_model",
           "InferenceProgram"]

_state = threading.local()


def _tls():
    if not hasattr(_state, "mode"):
        _state.mode = False
        _state.main = Program()
        _state.startup = Program()
    return _state


def enable_static():
    _tls().mode = True


def disable_static():
    _tls().mode = False


def in_static_mode() -> bool:
    return getattr(_state, "mode", False)


def default_main_program() -> "Program":
    return _tls().main


def default_startup_program() -> "Program":
    return _tls().startup


class program_guard:
    """reference fluid.program_guard: swap the default programs."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        t = _tls()
        self._saved = (t.main, t.startup)
        t.main = self.main
        if self.startup is not None:
            t.startup = self.startup
        return self.main

    def __exit__(self, *exc):
        t = _tls()
        t.main, t.startup = self._saved
        return False


class Variable:
    """Symbolic graph value (reference framework.py Variable). Produced
    by static.data (graph input) or by a recorded op."""

    _counter = 0

    def __init__(self, shape, dtype, name=None, producer=None,
                 out_index=0, program=None):
        if name is None:
            Variable._counter += 1
            name = f"_var_{Variable._counter}"
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.producer = producer          # _Node or None (graph input)
        self.out_index = out_index
        self.stop_gradient = True
        # owning program (reference Variable.block.program): minimize()
        # must land on the program the loss was RECORDED onto, not on
        # whatever default is active when minimize is called
        self.program = program

    # a minimal operator surface; everything routes through the public
    # ops, which record via apply()
    def _binop(self, other, opname):
        from .. import tensor as T
        return getattr(T, opname)(self, other)

    def __add__(self, o):
        return self._binop(o, "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "subtract")

    def __mul__(self, o):
        return self._binop(o, "multiply")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "divide")

    def __pow__(self, o):
        return self._binop(o, "pow")

    def __neg__(self):
        from .. import tensor as T
        return T.scale(self, -1.0)

    def __matmul__(self, o):
        from .. import tensor as T
        return T.matmul(self, o)

    def sum(self, axis=None, keepdim=False):
        from .. import tensor as T
        return T.sum(self, axis=axis, keepdim=keepdim)

    def mean(self, axis=None, keepdim=False):
        from .. import tensor as T
        return T.mean(self, axis=axis, keepdim=keepdim)

    def reshape(self, shape):
        from .. import tensor as T
        return T.reshape(self, shape)

    def astype(self, dtype):
        from .. import tensor as T
        return T.cast(self, dtype)

    def __repr__(self):
        kind = "data" if self.producer is None else "op"
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, {kind})")


class _Node:
    """One recorded op: fn over (Variable | captured Tensor | constant)
    inputs, with n_outputs Variables."""

    __slots__ = ("fn", "inputs", "name", "outputs", "multi")

    def __init__(self, fn, inputs, name, multi):
        self.fn = fn
        self.inputs = inputs          # list of Variable/Tensor/raw
        self.name = name
        self.multi = multi
        self.outputs: List[Variable] = []


class Program:
    """An ordered op list + the training hook minimize() installs."""

    def __init__(self):
        self.nodes: List[_Node] = []
        self.inputs: Dict[str, Variable] = {}
        # (loss_var, [(param_tensor, name)], optimizer) once minimize ran
        self._train: Optional[Tuple] = None
        # (capture_tensor, variable) pairs the Executor fetches on every
        # run and writes back into the capture — stateful side updates
        # (batch_norm moving averages) in an otherwise functional graph
        self._updates: List[Tuple] = []
        # test clones run batch_norm with moving statistics instead of
        # batch statistics (the training-mode flag capture is zeroed at
        # run time)
        self._for_test = False
        self._version = 0

    def _add_input(self, var: Variable):
        self.inputs[var.name] = var
        self._version += 1

    def _add_node(self, node: _Node):
        self.nodes.append(node)
        self._version += 1

    def global_block(self):
        return self  # block surface: vars/ops of the single block

    @property
    def ops(self):
        return self.nodes

    def clone(self, for_test=False):
        import copy
        p = Program()
        p.nodes = list(self.nodes)
        p.inputs = dict(self.inputs)
        if not for_test:
            # test clones keep the ops but drop the stateful writebacks
            # (reference clone(for_test=True) prunes momentum updates)
            p._updates = list(self._updates)
            p._train = copy.copy(self._train)
        p._for_test = bool(for_test) or self._for_test
        return p

    def __repr__(self):
        return (f"Program(ops={len(self.nodes)}, "
                f"inputs={sorted(self.inputs)})")


def record_data(name, shape, dtype) -> Variable:
    prog = default_main_program()
    var = Variable(shape, dtype, name=name, program=prog)
    prog._add_input(var)
    return var


def maybe_record(fn, args, name, amp_cast=None):
    """Called from core.autograd.apply when static mode is on and any
    arg is a Variable. Returns the output Variable(s) or None."""
    from ..core.tensor import Tensor

    if not any(isinstance(a, Variable) for a in args):
        return None

    node = _Node(fn, list(args), name, multi=False)

    def aval(a):
        if isinstance(a, Variable):
            shape = tuple(1 if s in (None, -1) else int(s)
                          for s in a.shape)
            return jax.ShapeDtypeStruct(shape, a.dtype)
        if isinstance(a, Tensor):
            return jax.ShapeDtypeStruct(tuple(a.data.shape), a.data.dtype)
        return a

    out = jax.eval_shape(fn, *[aval(a) for a in args])
    multi = isinstance(out, (tuple, list))
    node.multi = multi
    outs = tuple(out) if multi else (out,)
    prog = default_main_program()
    out_vars = tuple(
        Variable(o.shape, o.dtype, producer=node, out_index=i,
                 program=prog)
        for i, o in enumerate(outs))
    node.outputs = list(out_vars)
    prog._add_node(node)
    return out_vars if multi else out_vars[0]


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------
def _collect(fetch_vars: Sequence[Variable]):
    """Topo-order the subgraph feeding the fetches; returns (nodes,
    captured tensor list, input variables)."""
    from ..core.tensor import Tensor
    nodes, caps, inputs = [], [], []
    seen_nodes, seen_caps, seen_inputs = set(), set(), set()

    def visit_var(v: Variable):
        if v.producer is None:
            if id(v) not in seen_inputs:
                seen_inputs.add(id(v))
                inputs.append(v)
            return
        visit_node(v.producer)

    def visit_node(n: _Node):
        if id(n) in seen_nodes:
            return
        seen_nodes.add(id(n))
        for a in n.inputs:
            if isinstance(a, Variable):
                visit_var(a)
            elif isinstance(a, Tensor) and id(a) not in seen_caps:
                seen_caps.add(id(a))
                caps.append(a)
        nodes.append(n)

    for v in fetch_vars:
        visit_var(v)
    return nodes, caps, inputs


def _run_graph(nodes, caps, inputs, fetch_vars, cap_arrays, feed_arrays):
    """Execute the recorded ops over concrete arrays (jit-traceable)."""
    from ..core.tensor import Tensor
    env: Dict[int, Any] = {}
    for v, a in zip(inputs, feed_arrays):
        env[id(v)] = a
    cap_env = {id(t): a for t, a in zip(caps, cap_arrays)}

    for n in nodes:
        vals = []
        for a in n.inputs:
            if isinstance(a, Variable):
                vals.append(env[id(a)])
            elif isinstance(a, Tensor):
                vals.append(cap_env[id(a)])
            else:
                vals.append(a)
        out = n.fn(*vals)
        outs = tuple(out) if n.multi else (out,)
        for v, o in zip(n.outputs, outs):
            env[id(v)] = o
    return [env[id(v)] for v in fetch_vars]


class Executor:
    """reference executor.py Executor: run(program, feed, fetch_list).
    The jitted graph runner is cached per (program version, fetches,
    feed shapes)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, Any] = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        if isinstance(program, InferenceProgram):
            outs = program.run(feed)
            return [np.asarray(o) for o in outs] if return_numpy else outs
        fetch_list = list(fetch_list or [])
        if not fetch_list and not program._train and not program.nodes:
            return []  # startup program: params already initialized

        train = program._train
        loss_var = train[0] if train else None
        fetch_vars = [v for v in fetch_list]
        for v in fetch_vars:
            if not isinstance(v, Variable):
                raise TypeError(f"fetch_list entries must be static "
                                f"Variables, got {type(v)}")
        # stateful side updates (batch_norm moving averages) ride along
        # as extra fetches and are written back into their captures —
        # but only when the producing op is ALREADY in the fetched
        # closure: fetching a branch that doesn't touch batch_norm must
        # neither execute it, demand its feeds, nor advance its moving
        # statistics. An update var is another output of a node the
        # fetch already runs, so riders are free.
        base_roots = fetch_vars + ([loss_var] if train else [])
        in_closure = {id(n) for n in _collect(base_roots)[0]}
        updates = [(t, v) for t, v in program._updates
                   if v.producer is not None
                   and id(v.producer) in in_closure]
        fetch_vars = fetch_vars + [v for _, v in updates]
        roots = fetch_vars + ([loss_var] if train else [])
        nodes, caps, input_vars = _collect(roots)
        missing = [v.name for v in input_vars if v.name not in feed]
        if missing:
            raise ValueError(f"feed is missing graph inputs: {missing}")
        feed_arrays = [jnp.asarray(feed[v.name]) for v in input_vars]

        key = (id(program), program._version,
               tuple(id(v) for v in roots),
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               bool(train))
        runner = self._cache.get(key)
        if runner is None:
            runner = self._build(program, nodes, caps, input_vars,
                                 fetch_vars, train)
            self._cache[key] = runner
        run_caps = caps
        if program._for_test:
            # zero every batch_norm training-mode flag: the clone's
            # recorded ops then normalize with the captured moving
            # statistics. Flags are runtime arguments of the compiled
            # runner, so this needs no retrace and never touches the
            # original training program's captures.
            from ..core.tensor import Tensor as _T
            run_caps = [_T(jnp.zeros_like(t.data))
                        if getattr(t, "_bn_train_flag", False) else t
                        for t in caps]
        outs = runner(run_caps, feed_arrays)
        if updates:
            n_fetch = len(outs) - len(updates)
            for (t, _), val in zip(updates, outs[n_fetch:]):
                t._data = jnp.asarray(val, dtype=t._data.dtype)
            outs = outs[:n_fetch]
        # advance per-call-site iteration counters (nce negative
        # resampling etc.): captures are runtime args of the compiled
        # step, so the bump is visible next run without a retrace
        for t in caps:
            if getattr(t, "_iteration_counter", False):
                t._data = t._data + 1
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs

    def _build(self, program, nodes, caps, input_vars, fetch_vars,
               train):
        if not train:
            fn = jax.jit(
                lambda cap_arrays, feed_arrays: _run_graph(
                    nodes, caps, input_vars, fetch_vars, cap_arrays,
                    feed_arrays))

            def run_infer(cap_tensors, feed_arrays):
                return fn([t.data for t in cap_tensors], feed_arrays)
            return run_infer

        loss_var, params, optimizer = train
        param_ids = {id(p) for p, _ in params}
        # captured tensors that are NOT trained stay constants-by-ref
        frozen = [t for t in caps if id(t) not in param_ids]
        trained = [p for p, _ in params if any(id(p) == id(c)
                                               for c in caps)]

        def step(param_arrays, opt_state, frozen_arrays, feed_arrays,
                 lr, step_no):
            # lr/step_no are ARGUMENTS, not trace-time constants: LR
            # schedules and Adam bias correction must advance across
            # exe.run calls without a retrace
            fz = {id(t): a for t, a in zip(frozen, frozen_arrays)}

            def loss_of(p_arrays):
                tr = {id(p): a for p, a in zip(trained, p_arrays)}
                ca = [tr.get(id(t), fz.get(id(t))) for t in caps]
                vals = _run_graph(nodes, caps, input_vars,
                                  fetch_vars + [loss_var], ca,
                                  feed_arrays)
                return vals[-1].astype(jnp.float32).sum(), vals[:-1]

            (_, fetches), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(param_arrays))
            new_params, new_state = [], []
            for i, (p, g, s) in enumerate(zip(trained, grads, opt_state)):
                optimizer._cur_param_name = p.name
                optimizer._cur_param = p
                g = optimizer._apply_decay(param_arrays[i], g, p)
                np_, ns_ = optimizer._update(
                    param_arrays[i], g, s, lr, step_no)
                new_params.append(np_.astype(param_arrays[i].dtype))
                new_state.append(ns_)
            return new_params, new_state, fetches

        jit_step = jax.jit(step)

        def run_train(cap_tensors, feed_arrays):
            # accumulators live on the optimizer, like eager step()
            state = []
            for p in trained:
                key = p.name
                if key not in optimizer._accumulators:
                    optimizer._accumulators[key] = \
                        optimizer._init_accumulators(p.data)
                state.append(optimizer._accumulators[key])
            new_params, new_state, fetches = jit_step(
                [p.data for p in trained], state,
                [t.data for t in frozen], feed_arrays,
                jnp.asarray(optimizer.get_lr(), jnp.float32),
                jnp.asarray(optimizer._step_count + 1, jnp.int32))
            for p, a, s in zip(trained, new_params, new_state):
                p._data = a
                optimizer._accumulators[p.name] = s
            optimizer._step_count += 1
            return fetches
        return run_train


def record_gradients(targets, wrt, name="gradients"):
    """Record a node computing d(sum(targets))/d(wrt) into the program
    (reference backward.py gradients / append_backward grad-op chains —
    here one node whose fn is jax.grad over the re-run subgraph).

    `wrt` entries may be graph Variables (inputs OR intermediates: the
    dependency is cut at that variable, matching grad-op semantics) or
    captured Tensors/Parameters. Returns one grad Variable per entry.
    """
    from ..core.tensor import Tensor

    targets = [targets] if isinstance(targets, Variable) else list(targets)
    wrt = [wrt] if isinstance(wrt, (Variable, Tensor)) else list(wrt)
    nodes, caps, input_vars = _collect(targets)

    wrt_vars = [w for w in wrt if isinstance(w, Variable)]
    wrt_caps = [w for w in wrt if not isinstance(w, Variable)]
    cap_pos = {id(c): i for i, c in enumerate(caps)}
    for w in wrt_caps:
        if id(w) not in cap_pos:
            raise ValueError(
                f"gradients: tensor {getattr(w, 'name', w)} does not "
                f"feed the target(s)")

    n_in, n_cap, n_var = len(input_vars), len(caps), len(wrt_vars)

    def grad_fn(*vals):
        feeds = list(vals[:n_in])
        capvals = list(vals[n_in:n_in + n_cap])
        var_overrides = list(vals[n_in + n_cap:])

        def run_with(leaves):
            ov_caps = leaves[:len(wrt_caps)]
            ov_vars = leaves[len(wrt_caps):]
            ca = list(capvals)
            for w, v in zip(wrt_caps, ov_caps):
                ca[cap_pos[id(w)]] = v
            env = {id(v): a for v, a in zip(input_vars, feeds)}
            # seed the cut points FIRST: a node output already in env is
            # never overwritten, so the dependency stops here
            for w, v in zip(wrt_vars, ov_vars):
                env[id(w)] = v
            cap_env = {id(t): a for t, a in zip(caps, ca)}
            for n in nodes:
                if all(id(o) in env for o in n.outputs):
                    continue
                ins = [env[id(a)] if isinstance(a, Variable)
                       else cap_env[id(a)] if isinstance(a, Tensor)
                       else a for a in n.inputs]
                out = n.fn(*ins)
                outs = tuple(out) if n.multi else (out,)
                for v, o in zip(n.outputs, outs):
                    env.setdefault(id(v), o)
            total = 0.0
            for t in targets:
                total = total + env[id(t)].astype(jnp.float32).sum()
            return total

        leaves0 = [capvals[cap_pos[id(w)]] for w in wrt_caps] + \
            var_overrides
        grads = jax.grad(run_with)(leaves0)
        return tuple(g.astype(l.dtype) for g, l in zip(grads, leaves0))

    node = _Node(grad_fn, list(input_vars) + list(caps) + wrt_vars,
                 name, multi=True)
    prog = default_main_program()
    out_vars = []
    for i, w in enumerate(wrt):
        if isinstance(w, Variable):
            shape, dtype = w.shape, w.dtype
        else:
            shape, dtype = tuple(w.data.shape), w.data.dtype
        out_vars.append(Variable(shape, dtype, producer=node, out_index=i,
                                 program=prog,
                                 name=f"{getattr(w, 'name', 'x')}@GRAD"))
    # grad order follows leaves0 = caps-first then vars; remap to wrt's
    # order at output-index level
    order = []
    ci = vi = 0
    for w in wrt:
        if isinstance(w, Variable):
            order.append(len(wrt_caps) + vi)
            vi += 1
        else:
            order.append(ci)
            ci += 1
    for v, idx in zip(out_vars, order):
        v.out_index = idx
    node.outputs = sorted(out_vars, key=lambda v: v.out_index)
    prog._add_node(node)
    return out_vars


def install_minimize(program: Program, loss: Variable, optimizer):
    """Optimizer.minimize(symbolic loss) lands here: record the training
    hook (reference: minimize appended backward + optimizer ops)."""
    nodes, caps, _ = _collect([loss])
    from ..core.tensor import Parameter
    params = [(t, t.name) for t in caps
              if isinstance(t, Parameter) and t.trainable]
    if not params:
        raise ValueError(
            "minimize(loss): no trainable Parameters feed this loss")
    program._train = (loss, params, optimizer)
    program._version += 1


class InferenceProgram:
    """Deserialized save_inference_model artifact: a compiled feed/fetch
    function Executor.run can execute (reference load_inference_model
    returns a pruned Program; here the pruned program IS the serialized
    StableHLO export)."""

    def __init__(self, exported, feed_names, n_outputs):
        self.exported = exported
        self.feed_names = list(feed_names)
        self.n_outputs = int(n_outputs)

    def run(self, feed):
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise ValueError(f"feed is missing inputs: {missing}")
        args = [jnp.asarray(feed[n]) for n in self.feed_names]
        out = self.exported.call(*args)
        return list(out) if isinstance(out, (tuple, list)) else [out]


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, **configs) -> str:
    """Export the pruned static subgraph feeding `fetch_vars` as
    serialized StableHLO with parameters BAKED at save time (reference
    static.save_inference_model: prune + freeze persistables).
    feed_vars order defines the feed signature."""
    import pickle

    from jax import export as jexport

    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    nodes, caps, input_vars = _collect(fetch_vars)
    declared = {id(v) for v in feed_vars}
    extra = [v.name for v in input_vars if id(v) not in declared]
    if extra:
        raise ValueError(
            f"fetch_vars depend on inputs not in feed_vars: {extra}")

    cap_arrays = [t.data for t in caps]  # frozen at save time

    def fn(*feed_arrays):
        env_feeds = {id(v): a for v, a in zip(feed_vars, feed_arrays)}
        ordered = [env_feeds[id(v)] for v in input_vars]
        return tuple(_run_graph(nodes, caps, input_vars, fetch_vars,
                                cap_arrays, ordered))

    # None dims export as SYMBOLIC dims so the artifact serves any batch
    avals = []
    scope = jexport.SymbolicScope()
    n_sym = 0
    for v in feed_vars:
        dims = []
        for s in v.shape:
            if s in (None, -1):
                n_sym += 1
                dims.append(jexport.symbolic_shape(
                    f"d{n_sym}", scope=scope)[0])
            else:
                dims.append(int(s))
        avals.append(jax.ShapeDtypeStruct(tuple(dims), v.dtype))
    exported = jexport.export(jax.jit(fn))(*avals)

    import os
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    meta = {"feed_names": [v.name for v in feed_vars],
            "n_outputs": len(fetch_vars)}
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)
    return path_prefix


def load_inference_model(path_prefix: str, executor=None):
    """Returns (InferenceProgram, feed_names, fetch_count) — the
    reference's [program, feed_target_names, fetch_targets] shape."""
    import pickle

    from jax import export as jexport
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    prog = InferenceProgram(exported, meta["feed_names"],
                            meta["n_outputs"])
    return prog, prog.feed_names, prog.n_outputs
