"""paddle.distribution parity — Normal / Uniform / Categorical.

Reference: python/paddle/distribution.py (Distribution base:
sample/entropy/log_prob/probs/kl_divergence; Uniform low/high; Normal
loc/scale; Categorical logits). TPU-native: functional jax.random keys
drawn from the framework generator (core.random.next_key), math in
jnp — every method is traceable so distributions work inside compiled
programs as well as eagerly.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .core import random as prandom
from .core.tensor import Tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "kl_divergence"]


def _arr(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        a = x.data
    else:
        a = jnp.asarray(x)
    return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) \
        or jnp.issubdtype(a.dtype, jnp.integer) else a


class Distribution:
    """Base (reference distribution.py Distribution)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))

    def kl_divergence(self, other) -> Tensor:
        raise NotImplementedError

    @staticmethod
    def _extend(shape, base):
        return tuple(shape) + tuple(base)


class Uniform(Distribution):
    """U[low, high) (reference distribution.py Uniform)."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=(), seed=0):
        key = prandom.next_key()
        base = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        u = jax.random.uniform(key, self._extend(shape, base),
                               jnp.float32)
        return Tensor(self.low + u * (self.high - self.low))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))


class Normal(Distribution):
    """N(loc, scale^2) (reference distribution.py Normal)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=(), seed=0):
        key = prandom.next_key()
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(key, self._extend(shape, base),
                                jnp.float32)
        return Tensor(self.loc + eps * self.scale)

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) +
                      jnp.log(self.scale) +
                      jnp.zeros_like(self.loc))

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var) -
                      jnp.log(self.scale) -
                      0.5 * math.log(2 * math.pi))

    def kl_divergence(self, other: "Normal") -> Tensor:
        # KL(self || other), reference Normal.kl_divergence
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference
    distribution.py Categorical)."""

    def __init__(self, logits, name=None):
        self.logits = _arr(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)

    def sample(self, shape=(), seed=0):
        key = prandom.next_key()
        return Tensor(jax.random.categorical(
            key, self.logits, shape=tuple(shape) + self.logits.shape[:-1]))

    def entropy(self):
        p = jnp.exp(self._log_p)
        return Tensor(-jnp.sum(p * self._log_p, axis=-1))

    def log_prob(self, value):
        v = jnp.asarray(_arr(value, dtype=jnp.int32), jnp.int32)
        return Tensor(jnp.take_along_axis(
            self._log_p, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))

    def kl_divergence(self, other: "Categorical") -> Tensor:
        p = jnp.exp(self._log_p)
        return Tensor(jnp.sum(p * (self._log_p - other._log_p), axis=-1))


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    """paddle.distribution.kl_divergence dispatch."""
    return p.kl_divergence(q)
