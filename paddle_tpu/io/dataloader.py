"""DataLoader: batched, prefetching host->device feed.

Reference: python/paddle/fluid/reader.py DataLoader (:149) +
dataloader_iter.py multiprocess workers + C++ double-buffer
operators/reader/buffered_reader.cc.

Design (TPU-native): worker threads run `collate(dataset[i] for i in
batch)` concurrently into a bounded queue (numpy decode releases the
GIL); the consumer converts to device arrays, which under JAX is an async
transfer — so while step N computes, batch N+1 is already crossing PCIe.
That is exactly buffered_reader.cc's stream/event overlap without any
explicit stream code.
"""
from __future__ import annotations

import itertools
import os
import threading
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    """Stack a list of samples into batch arrays (reference
    dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s.data) for s in batch], axis=0)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn(list(f)) for f in zip(*batch))
    return np.asarray(batch)


class _Prefetcher:
    """Thread-pool prefetch of collated batches into a bounded reorder
    buffer.

    Ordered hand-off: each worker pulls (seq, thunk) under the condition
    lock and posts (seq, result); the consumer emits strictly in seq
    order.  Hygiene guarantees:

    - REAL backpressure: workers stall when results + in-flight tasks
      reach capacity (the old version only throttled the consumer, so
      workers could collate the whole dataset into RAM);
    - exceptions from the batch ITERATOR itself (not just from thunks)
      surface on the consumer instead of silently killing a worker and
      deadlocking the emit loop;
    - leaving the loop early (break / GeneratorExit) wakes every worker
      via the stop flag and joins them — no leaked daemon threads
      spinning on a dead iterator.
    """

    def __init__(self, make_batch_iter, num_workers, capacity):
        self._make_iter = make_batch_iter
        self._num_workers = max(1, num_workers)
        self._capacity = max(1, capacity)

    def __iter__(self):
        task_iter = enumerate(self._make_iter())
        cond = threading.Condition()
        iter_lock = threading.Lock()  # serializes next(task_iter) ONLY
        results = {}  # seq -> collated batch | raised exception
        state = {"done": False, "stop": False, "inflight": 0,
                 "next_emit": 0, "iter_error": None}

        def worker():
            while True:
                with cond:
                    # reserve an in-flight slot BEFORE pulling a task so
                    # the consumer's done-and-drained exit check stays
                    # sound while we hold only the iterator lock
                    while (not state["stop"] and not state["done"] and
                           len(results) + state["inflight"] >=
                           self._capacity):
                        cond.wait(timeout=0.1)
                    if state["stop"] or state["done"]:
                        return
                    state["inflight"] += 1
                # pull OUTSIDE the condition lock: a slow batch iterator
                # (streaming dataset) must not block the consumer from
                # emitting batches that are already collated
                got, err = False, None
                with iter_lock:
                    try:
                        seq, thunk = next(task_iter)
                        got = True
                    except StopIteration:
                        pass
                    except BaseException as e:
                        # the iterator itself failed: deliver it instead
                        # of leaving the consumer waiting forever
                        err = e
                if not got:
                    with cond:
                        if err is not None:
                            state["iter_error"] = err
                        state["done"] = True
                        state["inflight"] -= 1
                        cond.notify_all()
                    return
                try:
                    res = thunk()
                except BaseException as e:  # propagate to consumer
                    res = e
                with cond:
                    results[seq] = res
                    state["inflight"] -= 1
                    cond.notify_all()

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"pd-prefetch-{i}")
                   for i in range(self._num_workers)]
        for t in threads:
            t.start()

        try:
            while True:
                with cond:
                    while True:
                        if state["next_emit"] in results:
                            res = results.pop(state["next_emit"])
                            state["next_emit"] += 1
                            cond.notify_all()  # frees worker capacity
                            break
                        if state["done"] and state["inflight"] == 0:
                            if state["iter_error"] is not None:
                                raise state["iter_error"]
                            return
                        cond.wait(timeout=0.1)
                if isinstance(res, BaseException):
                    raise res
                yield res
        finally:
            with cond:
                state["stop"] = True
                cond.notify_all()
            for t in threads:
                t.join(timeout=5)


class _MultiprocessIter:
    """True multiprocess workers over native shared-memory rings
    (reference fluid/dataloader/dataloader_iter.py:230-378 +
    imperative/data_loader.cc): worker process w collates batches
    w, w+W, ... and pushes pickled frames into ITS ring
    (io/native/shm_ring.c); the trainer pops ring seq % W, so original
    batch order is preserved with no reorder buffer and no Python queue
    locks on the hot path.

    FORK CAVEAT (same as the reference's fork workers): the child is a
    fork of a process whose JAX runtime is multithreaded, so dataset
    __getitem__ / collate_fn / worker_init_fn must stay numpy-only —
    touching jax/paddle Tensors in a worker can deadlock on inherited
    locks. A dead worker is detected by liveness polling and surfaces
    as a RuntimeError rather than a hang."""

    def __init__(self, loader, batch_lists, num_workers, capacity_bytes,
                 timeout_ms, worker_init_fn=None, worker_restarts=0):
        self.loader = loader
        self.batch_lists = batch_lists
        self.num_workers = num_workers
        self.capacity = capacity_bytes
        self.timeout_ms = timeout_ms
        self.worker_init_fn = worker_init_fn
        # bounded revive budget PER WORKER for crash-style deaths (OOM
        # kill, segfault): the replacement process resumes at the first
        # batch the consumer has not received. Python-level dataset
        # exceptions are NEVER retried — they are deterministic and the
        # traceback is re-raised in the trainer instead.
        self.worker_restarts = max(0, int(worker_restarts))

    class _WorkerDied(Exception):
        def __init__(self, w, seq, exitcode):
            self.w, self.seq, self.exitcode = w, seq, exitcode

    def __iter__(self):
        import multiprocessing as mp
        import pickle
        import tempfile
        import traceback as tb_mod

        from .shm_ring import RingClosed, RingTimeout, ShmRing

        ctx = mp.get_context("fork")
        W = self.num_workers
        rings = [ShmRing.create(self.capacity) for _ in range(W)]
        ds, collate = self.loader.dataset, self.loader.collate_fn
        init_fn = self.worker_init_fn
        # traceback spill files: the ring push of an error frame can
        # itself fail (ring full, ring torn down); the file survives the
        # worker so the consumer ALWAYS gets the real traceback instead
        # of a bare "worker died" (the old path swallowed it)
        err_dir = tempfile.mkdtemp(prefix="pd_dl_err_")
        err_path = [os.path.join(err_dir, f"worker{w}.err")
                    for w in range(W)]

        def work(w, ring_name, batches):
            ring = ShmRing.attach(ring_name)
            done = 0
            try:
                _set_worker_info(WorkerInfo(w, W, ds))
                if init_fn is not None:
                    init_fn(w)
                for idxs in batches:
                    payload = pickle.dumps(
                        ("b", collate([ds[i] for i in idxs])),
                        protocol=pickle.HIGHEST_PROTOCOL)
                    ring.push(payload)
                    done += 1
                    from ..testing import faults as _faults
                    _faults.maybe_kill_worker(w, done)
            except Exception:
                trace = tb_mod.format_exc()
                try:
                    with open(err_path[w], "w") as f:
                        f.write(trace)
                except OSError:
                    pass
                try:
                    ring.push(pickle.dumps(("e", trace)))
                except Exception:
                    pass
            finally:
                ring.close_writer()

        def spawn(w, skip):
            """Start (or restart) worker w at its skip-th batch."""
            p = ctx.Process(target=work,
                            args=(w, rings[w].name,
                                  self.batch_lists[w::W][skip:]),
                            daemon=True)
            p.start()
            return p

        produced = [0] * W       # batches the CONSUMER popped per worker
        revives = [self.worker_restarts] * W
        procs = [spawn(w, 0) for w in range(W)]

        def pop_watched(seq):
            """Pop with liveness polling: a SIGKILLed worker (OOM) never
            runs close_writer, so an unbounded pop would hang silently —
            poll in slices and check the process between them."""
            import time as _time
            w = seq % W
            budget = self.timeout_ms
            deadline = (_time.monotonic() + budget / 1000.0) \
                if budget and budget > 0 else None
            while True:
                try:
                    return rings[w].pop(timeout_ms=500)
                except RingTimeout:
                    if not procs[w].is_alive():
                        # the worker may have exited cleanly AFTER
                        # pushing this batch (final-pop race): drain the
                        # ring once more before declaring it dead
                        try:
                            return rings[w].pop(timeout_ms=100)
                        except (RingTimeout, RingClosed):
                            raise self._WorkerDied(
                                w, seq, procs[w].exitcode)
                except RingClosed:
                    raise self._WorkerDied(w, seq, procs[w].exitcode)
                if deadline and _time.monotonic() > deadline:
                    raise RuntimeError(
                        f"dataloader worker {w} timed out")

        def worker_error(w):
            """Spilled traceback from worker w, if it recorded one."""
            try:
                with open(err_path[w]) as f:
                    return f.read().strip() or None
            except OSError:
                return None

        def revive_or_raise(dead):
            w = dead.w
            trace = worker_error(w)
            if trace is not None:
                # deterministic dataset/collate exception: re-raise the
                # captured traceback, do not burn a restart on it
                raise RuntimeError(
                    f"dataloader worker {w} failed:\n{trace}")
            if revives[w] <= 0:
                raise RuntimeError(
                    f"dataloader worker {w} died before producing batch "
                    f"{dead.seq} (exitcode {dead.exitcode}, "
                    f"{self.worker_restarts} restart(s) exhausted)")
            revives[w] -= 1
            procs[w].join(5)
            # the old ring may hold frames the consumer never popped (or
            # a half-written frame from the kill): replace it wholesale
            # and re-produce from the consumer's high-water mark
            rings[w].destroy()
            rings[w] = ShmRing.create(self.capacity)
            procs[w] = spawn(w, produced[w])

        try:
            for seq in range(len(self.batch_lists)):
                w = seq % W
                while True:
                    try:
                        raw = pop_watched(seq)
                        break
                    except self._WorkerDied as dead:
                        revive_or_raise(dead)
                kind, payload = pickle.loads(raw)
                if kind == "e":
                    raise RuntimeError(
                        f"dataloader worker {w} failed:\n{payload}")
                produced[w] += 1
                yield payload
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(5)
            for r in rings:
                r.destroy()
            import shutil
            shutil.rmtree(err_dir, ignore_errors=True)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, shm_ring_capacity=32 << 20,
                 worker_restarts=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        # bounded revive budget for crashed (not failed) workers; the
        # env default keeps launch configs out of user code
        if worker_restarts is None:
            worker_restarts = int(os.environ.get(
                "PADDLE_TPU_WORKER_RESTARTS", "0"))
        self.worker_restarts = max(0, int(worker_restarts))
        self.use_buffer_reader = use_buffer_reader
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.shm_ring_capacity = shm_ring_capacity
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_ds:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _to_tensors(self, collated):
        if isinstance(collated, dict):
            return {k: self._to_tensors(v) for k, v in collated.items()}
        if isinstance(collated, (tuple, list)):
            return [self._to_tensors(v) for v in collated]
        if isinstance(collated, np.ndarray):
            return Tensor(collated)
        if isinstance(collated, Tensor):
            return collated
        return collated

    def _batch_thunks(self):
        """Yield zero-arg thunks producing collated numpy batches."""
        collate = self.collate_fn
        if self._iterable_ds:
            def gen():
                it = iter(self.dataset)
                while True:
                    batch = list(itertools.islice(it, self.batch_size))
                    if not batch:
                        return
                    if len(batch) < self.batch_size and self.drop_last:
                        return
                    yield (lambda b=batch: collate(b))
            return gen()
        if self.batch_sampler is None:
            ds = self.dataset
            return ((lambda i=i: collate([ds[i]]))
                    for i in range(len(ds)))
        ds = self.dataset
        return ((lambda idxs=idxs: collate([ds[i] for i in idxs]))
                for idxs in self.batch_sampler)

    def _can_multiprocess(self) -> bool:
        if (self.num_workers <= 0 or not self.use_shared_memory or
                self._iterable_ds or self.batch_sampler is None):
            return False
        import multiprocessing as mp
        if "fork" not in mp.get_all_start_methods():
            return False  # pragma: no cover (non-POSIX)
        from .shm_ring import available
        return available()

    def __iter__(self):
        if self._can_multiprocess():
            mp_iter = _MultiprocessIter(
                self, list(self.batch_sampler), self.num_workers,
                self.shm_ring_capacity,
                int(self.timeout * 1000) if self.timeout else -1,
                self.worker_init_fn, worker_restarts=self.worker_restarts)
            for collated in mp_iter:
                yield self._to_tensors(collated)
        elif self.num_workers > 0 and self.use_buffer_reader:
            prefetcher = _Prefetcher(
                self._batch_thunks, self.num_workers,
                capacity=self.prefetch_factor * max(1, self.num_workers))
            for collated in prefetcher:
                yield self._to_tensors(collated)
        else:
            for thunk in self._batch_thunks():
                yield self._to_tensors(thunk())

    def __call__(self):
        return self.__iter__()


class WorkerInfo:
    """reference dataloader_iter.py WorkerInfo: id / num_workers /
    dataset visible inside a worker process."""

    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


_WORKER_INFO = None


def get_worker_info():
    """reference fluid/dataloader/dataloader_iter.py:133 — WorkerInfo in
    a dataloader worker process, None in the main process."""
    return _WORKER_INFO


def _set_worker_info(info):
    global _WORKER_INFO
    _WORKER_INFO = info
