"""DevicePrefetcher: overlap host->device transfer with compute.

The DataLoader's worker threads already overlap *decode/collate* with
the step; what still ran inside the step path was the ``device_put`` of
the collated batch (``SpmdTrainer.shard_batch``).  On a dispatch-bound
step loop that transfer serializes with dispatch: the host cannot queue
step N+1 before it finished placing batch N+1.

This wrapper moves the placement onto a background thread: while the
device runs step N, the thread ``device_put``s batches N+1..N+depth with
the trainer's batch sharding into a bounded queue.  The consumer then
feeds already-committed device arrays into ``train_step``, whose
``shard_batch`` fast-path recognizes them and skips the transfer.

Donation safety
---------------
``put_fn`` must produce FRESH committed arrays (a ``device_put`` of host
data does).  Prefetched buffers therefore never alias the trainer's
donated state: the compiled step donates only params/opt-state/buffers
(argnums 0..3), never the batch operands, and a rollback host snapshot
copies device state that was never handed to this queue.  Do not pass a
``put_fn`` that returns views of live training state.

Hygiene: worker exceptions surface on the consuming thread at the point
of the failed batch; ``close()`` (also called when the consumer exits
the loop early) drains the queue, unblocks and joins the thread.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Optional

__all__ = ["DevicePrefetcher"]

_BATCH, _ERROR, _END = 0, 1, 2


class DevicePrefetcher:
    """Iterate device-committed batches, transferred ``depth`` ahead.

    Parameters
    ----------
    host_iter : iterable of host batches (numpy / Tensor pytrees).
    put_fn : callable(batch) -> device batch.  Runs on the background
        thread; must return fresh committed arrays (e.g.
        ``SpmdTrainer.shard_batch``).
    depth : how many batches may be in flight on the device ahead of the
        consumer (bounded queue size).
    timings : optional dict accumulating ``data_wait_ms`` /
        ``h2d_ms`` (the trainer's step-time breakdown).
    """

    def __init__(self, host_iter: Iterable, put_fn: Callable[[Any], Any],
                 depth: int = 2, timings: Optional[dict] = None):
        self._iter = iter(host_iter)
        self._put = put_fn
        self._depth = max(1, int(depth))
        self._timings = timings if timings is not None else {}
        self._timings.setdefault("data_wait_ms", 0.0)
        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.batches_prefetched = 0

    # -- producer ------------------------------------------------------
    def _post(self, item) -> bool:
        """Enqueue, yielding to the stop flag; True if delivered."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            while True:
                # check stop BEFORE pulling: close() must not consume an
                # extra batch from a caller-owned single-pass stream
                if self._stop.is_set():
                    return
                try:
                    batch = next(self._iter)
                except StopIteration:
                    break
                dev = self._put(batch)
                self.batches_prefetched += 1
                if not self._post((_BATCH, dev)):
                    return
        except BaseException as e:  # propagate to the consumer
            self._post((_ERROR, e))
            return
        self._post((_END, None))

    def _ensure_started(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="pd-device-prefetch", daemon=True)
            self._thread.start()

    # -- consumer ------------------------------------------------------
    def __iter__(self):
        self._ensure_started()
        try:
            while True:
                t0 = time.perf_counter()
                while True:
                    try:
                        kind, payload = self._q.get(timeout=0.5)
                        break
                    except queue.Empty:
                        # a worker killed without posting its END/ERROR
                        # frame must not hang the training loop.  The
                        # producer may have posted its FINAL frame and
                        # exited between our timeout and this check, so
                        # drain once more before declaring it dead
                        if not self.alive:
                            try:
                                kind, payload = self._q.get_nowait()
                                break
                            except queue.Empty:
                                raise RuntimeError(
                                    "device prefetch thread died without "
                                    "delivering a batch")
                dt = (time.perf_counter() - t0) * 1e3
                self._timings["data_wait_ms"] += dt
                from ..observability import spans as _spans
                tr = _spans.tracer()
                if tr.active:
                    now = tr.now_us()
                    tr.complete("data_wait", now - dt * 1e3, dt * 1e3,
                                cat="train")
                if kind == _END:
                    return
                if kind == _ERROR:
                    raise payload
                yield payload
        finally:
            self.close()

    def __enter__(self):
        self._ensure_started()
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self, join_timeout: float = 5.0):
        """Stop the transfer thread and reclaim the queue. Safe to call
        repeatedly and from ``finally`` blocks on early loop exit."""
        self._stop.set()
        # drain so a producer blocked on put() observes the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()
