"""Python bindings for the native shared-memory ring (io/native/shm_ring.c).

The extension is compiled on first use with the system C compiler into a
content-addressed cache (no pip/pybind11 needed — plain ctypes over a
tiny C ABI), mirroring how the reference ships mmap_allocator.cc inside
the wheel. `available()` gates gracefully: no compiler -> the DataLoader
falls back to its thread prefetcher.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from multiprocessing import shared_memory
from typing import Optional

__all__ = ["ShmRing", "available"]

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "native", "shm_ring.c")
_lib = None
_lib_err: Optional[str] = None


def _build() -> ctypes.CDLL:
    global _lib, _lib_err
    if _lib is not None:
        return _lib
    if _lib_err is not None:
        raise RuntimeError(_lib_err)
    try:
        cc = (os.environ.get("CC") or shutil.which("cc") or
              shutil.which("gcc") or shutil.which("clang"))
        if cc is None:
            raise RuntimeError("no C compiler on PATH")
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        cache = os.path.join(tempfile.gettempdir(),
                             f"paddle_tpu_native_{os.getuid()}")
        os.makedirs(cache, exist_ok=True)
        so = os.path.join(cache, f"shm_ring_{digest}.so")
        if not os.path.exists(so):
            tmp = so + f".build{os.getpid()}"
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-std=c11", _SRC,
                 "-o", tmp],
                check=True, capture_output=True)
            os.replace(tmp, so)
        lib = ctypes.CDLL(so)
        lib.ring_needed.restype = ctypes.c_uint64
        lib.ring_needed.argtypes = [ctypes.c_uint64]
        lib.ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ring_close.argtypes = [ctypes.c_void_p]
        lib.ring_is_closed.argtypes = [ctypes.c_void_p]
        lib.ring_is_closed.restype = ctypes.c_int
        lib.ring_push.restype = ctypes.c_int
        lib.ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64, ctypes.c_long]
        lib.ring_peek.restype = ctypes.c_int64
        lib.ring_peek.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.ring_pop.restype = ctypes.c_int64
        lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64, ctypes.c_long]
        _lib = lib
        return lib
    except Exception as e:  # pragma: no cover - environment dependent
        _lib_err = f"shm_ring native build failed: {e}"
        raise RuntimeError(_lib_err) from e


def available() -> bool:
    try:
        _build()
        return True
    except Exception:
        return False


class RingClosed(Exception):
    pass


class RingTimeout(Exception):
    pass


class ShmRing:
    """Single-producer/single-consumer byte-frame ring in POSIX shared
    memory. One side `create()`s, the other `attach()`es by name."""

    def __init__(self, shm: shared_memory.SharedMemory, owner: bool):
        self._lib = _build()
        self._shm = shm
        self._owner = owner
        self._addr = ctypes.addressof(
            ctypes.c_char.from_buffer(shm.buf))

    @classmethod
    def create(cls, capacity: int = 32 << 20,
               name: Optional[str] = None) -> "ShmRing":
        lib = _build()
        size = int(lib.ring_needed(capacity))
        shm = shared_memory.SharedMemory(create=True, size=size, name=name)
        ring = cls(shm, owner=True)
        lib.ring_init(ring._addr, capacity)
        return ring

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        shm = shared_memory.SharedMemory(name=name)
        return cls(shm, owner=False)

    @property
    def name(self) -> str:
        return self._shm.name

    def push(self, payload: bytes, timeout_ms: int = -1):
        rc = self._lib.ring_push(self._addr, payload, len(payload),
                                 timeout_ms)
        if rc == 0:
            return
        if rc == -2:
            raise RingClosed("ring closed")
        if rc == -3:
            raise ValueError(
                f"frame of {len(payload)} bytes exceeds half the ring "
                f"capacity (the wrap-progress bound); raise DataLoader "
                f"shm_ring_capacity to > {2 * len(payload)} bytes")
        raise RingTimeout("push timed out")

    def pop(self, timeout_ms: int = -1) -> bytes:
        n = self._lib.ring_peek(self._addr, timeout_ms)
        if n == -2:
            raise RingClosed("ring closed and drained")
        if n == -1:
            raise RingTimeout("pop timed out")
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.ring_pop(self._addr, buf, int(n), timeout_ms)
        if got < 0:  # pragma: no cover - peek already qualified it
            raise RuntimeError(f"ring_pop rc={got}")
        return buf.raw[:got]

    def close_writer(self):
        """Producer signals end-of-stream (consumer drains then sees
        RingClosed)."""
        self._lib.ring_close(self._addr)

    def destroy(self):
        # release the ctypes view BEFORE closing the mmap or shm.close()
        # raises BufferError("cannot close exported pointers exist")
        self._addr = None
        import gc
        gc.collect()
        try:
            self._shm.close()
        except BufferError:  # pragma: no cover
            pass
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass
