"""paddle.io parity: Dataset / Sampler / DataLoader.

Reference: python/paddle/fluid/dataloader/ (dataset.py, batch_sampler.py,
dataloader_iter.py) + fluid/reader.py DataLoader (§2.6 of SURVEY.md) and
the C++ double-buffered reader (operators/reader/buffered_reader.cc).

TPU-native design: worker parallelism uses a thread pool feeding a
bounded prefetch queue (the reference forked processes because CUDA +
fork + Python made threads useless for CPU-bound decode; here the decode
work releases the GIL in numpy and the XLA device transfer is async, so
threads + double buffering deliver the same overlap without shared-memory
mmap plumbing). The final host->device stage pins the next batch onto the
accelerator while the current step runs — the buffered_reader.cc pattern.
"""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler)
from .dataloader import (DataLoader, default_collate_fn,  # noqa: F401
                         WorkerInfo, get_worker_info)
from .device_prefetch import DevicePrefetcher  # noqa: F401
from .in_memory import InMemoryDataset  # noqa: F401
