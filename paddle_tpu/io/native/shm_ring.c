/* Shared-memory SPSC ring buffer — the native core of the multiprocess
 * DataLoader.
 *
 * Reference role: paddle/fluid/operators/reader/buffered_reader.cc
 * (double-buffered async feed) + memory/allocation/mmap_allocator.cc +
 * imperative/data_loader.cc (shared-memory queues between dataloader
 * worker processes and the trainer).  TPU-native shape: one ring per
 * worker process living in POSIX shared memory; the worker pushes
 * length-framed pickled batches, the trainer process pops them without
 * any Python-level queue locks (single-producer/single-consumer,
 * lock-free with C11 atomics; waiting sides nanosleep-poll, which at
 * batch granularity costs nothing).
 *
 * Layout: [header][data region of `capacity` bytes]
 * Frames are 8-byte aligned: u64 payload length, then payload.  A
 * frame never wraps: if it does not fit contiguously, a WRAP marker
 * (len == ~0) is written (when >= 8 bytes remain) and the writer
 * continues at offset 0; the reader skips to the region start on
 * seeing the marker or when fewer than 8 contiguous bytes remain.
 */
#include <stdatomic.h>
#include <stdint.h>
#include <string.h>
#include <time.h>

typedef struct {
    uint64_t capacity;
    _Atomic uint64_t head;   /* bytes written, monotonic  */
    _Atomic uint64_t tail;   /* bytes consumed, monotonic */
    _Atomic uint32_t closed;
    uint32_t _pad;
    char data[];
} ring_t;

#define WRAP_MARKER 0xFFFFFFFFFFFFFFFFull

static void sleep_us(long us) {
    struct timespec ts = {0, us * 1000L};
    nanosleep(&ts, 0);
}

static uint64_t align8(uint64_t x) { return (x + 7ull) & ~7ull; }

uint64_t ring_needed(uint64_t capacity) {
    return sizeof(ring_t) + capacity;
}

void ring_init(void *mem, uint64_t capacity) {
    ring_t *r = (ring_t *)mem;
    r->capacity = capacity;
    atomic_store(&r->head, 0);
    atomic_store(&r->tail, 0);
    atomic_store(&r->closed, 0);
}

void ring_close(void *mem) {
    atomic_store(&((ring_t *)mem)->closed, 1);
}

int ring_is_closed(void *mem) {
    return (int)atomic_load(&((ring_t *)mem)->closed);
}

/* 0 = ok, -1 = timeout, -2 = closed */
int ring_push(void *mem, const void *buf, uint64_t len, long timeout_ms) {
    ring_t *r = (ring_t *)mem;
    uint64_t need = 8 + align8(len);
    long waited_us = 0;
    /* cap at capacity/2: when a wrap is required, contig < need <=
     * capacity/2 bounds contig + need < capacity, so a drained ring can
     * ALWAYS take the frame — larger frames could hit offsets where
     * wrap space never fits and spin forever. */
    if (need > r->capacity / 2) return -3;
    for (;;) {
        if (atomic_load(&r->closed)) return -2;
        uint64_t head = atomic_load(&r->head);
        uint64_t tail = atomic_load(&r->tail);
        uint64_t off = head % r->capacity;
        uint64_t contig = r->capacity - off;
        uint64_t total = (contig >= need) ? need : contig + need;
        if (head + total - tail <= r->capacity) {
            if (contig < need) {
                if (contig >= 8) {
                    uint64_t m = WRAP_MARKER;
                    memcpy(r->data + off, &m, 8);
                }
                head += contig;
                off = 0;
            }
            memcpy(r->data + off, &len, 8);
            memcpy(r->data + off + 8, buf, len);
            atomic_store(&r->head, head + need);
            return 0;
        }
        if (timeout_ms >= 0 && waited_us > timeout_ms * 1000L) return -1;
        sleep_us(200);
        waited_us += 200;
    }
}

/* next frame's payload length without consuming:
 * >=0 length, -1 timeout, -2 closed-and-drained */
int64_t ring_peek(void *mem, long timeout_ms) {
    ring_t *r = (ring_t *)mem;
    long waited_us = 0;
    for (;;) {
        uint64_t head = atomic_load(&r->head);
        uint64_t tail = atomic_load(&r->tail);
        if (head == tail) {
            if (atomic_load(&r->closed)) return -2;
            if (timeout_ms >= 0 && waited_us > timeout_ms * 1000L)
                return -1;
            sleep_us(200);
            waited_us += 200;
            continue;
        }
        uint64_t off = tail % r->capacity;
        uint64_t contig = r->capacity - off;
        uint64_t len;
        if (contig < 8) {
            atomic_store(&r->tail, tail + contig);
            continue;
        }
        memcpy(&len, r->data + off, 8);
        if (len == WRAP_MARKER) {
            atomic_store(&r->tail, tail + contig);
            continue;
        }
        return (int64_t)len;
    }
}

/* >=0 payload length, -1 timeout, -2 closed-and-drained, -3 too small */
int64_t ring_pop(void *mem, void *out, uint64_t maxlen, long timeout_ms) {
    ring_t *r = (ring_t *)mem;
    int64_t len = ring_peek(mem, timeout_ms);
    if (len < 0) return len;
    if ((uint64_t)len > maxlen) return -3;
    uint64_t tail = atomic_load(&r->tail);
    uint64_t off = tail % r->capacity;
    memcpy(out, r->data + off + 8, (size_t)len);
    atomic_store(&r->tail, tail + 8 + align8((uint64_t)len));
    return len;
}
