"""InMemoryDataset — slot-based CTR dataset with in-memory shuffle.

Reference: /root/reference/paddle/fluid/framework/data_set.h:157
(InMemoryDataset: load slot records into memory, local/global shuffle,
feed trainers) + python/paddle/fluid/dataset.py and the SlotRecord text
format of data_feed.cc ("label slot:feasign slot:feasign ...").

TPU-native shape: records parse into python dicts, shuffles are
in-memory permutations, and batches come out as dense numpy arrays —
sparse id slots as padded [B, max_ids] + lengths (the framework's
standard ragged convention) ready for Embedding(sparse=True) lookups or
PS pull_sparse; dense slots as [B, dim] float arrays.
"""
from __future__ import annotations

import hashlib
import random
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

__all__ = ["InMemoryDataset"]


class InMemoryDataset:
    def __init__(self, use_slots: Optional[Sequence[str]] = None,
                 dense_slots: Optional[Dict[str, int]] = None,
                 batch_size: int = 1, label_slot: str = "label"):
        """use_slots: sparse id slots to keep (None = keep all seen);
        dense_slots: name -> dim for float slots; label_slot: name under
        which leading label values are stored."""
        self.use_slots = list(use_slots) if use_slots else None
        self.dense_slots = dict(dense_slots or {})
        self.batch_size = int(batch_size)
        self.label_slot = label_slot
        self._records: List[dict] = []

    # ---- configuration (fluid.dataset API names) -----------------------
    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_use_var(self, slots: Sequence[str]):
        self.use_slots = list(slots)

    # ---- loading -------------------------------------------------------
    def parse_line(self, line: str) -> Optional[dict]:
        """SlotRecord text: 'label [label2 ...] slot:val slot:val ...'.
        Leading bare numbers are labels; 'name:value' pairs fill slots
        (sparse slots collect int ids, dense slots collect floats)."""
        parts = line.split()
        if not parts:
            return None
        rec: dict = {self.label_slot: []}
        for p in parts:
            if ":" not in p:
                rec[self.label_slot].append(float(p))
                continue
            name, val = p.split(":", 1)
            if name in self.dense_slots:
                rec.setdefault(name, []).append(float(val))
            elif self.use_slots is None or name in self.use_slots:
                rec.setdefault(name, []).append(int(val))
        return rec

    def load_into_memory(self, filelist: Sequence[str]):
        """Read every line of every file into memory (the reference's
        LoadIntoMemory over its file queue)."""
        for path in filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = self.parse_line(line)
                    if rec is not None:
                        self._records.append(rec)

    def set_records(self, records: Sequence[dict]):
        """Programmatic load (tests / in-process producers)."""
        self._records = list(records)

    # ---- shuffle -------------------------------------------------------
    def local_shuffle(self, seed: Optional[int] = None):
        random.Random(seed).shuffle(self._records)

    def global_shuffle(self, rank: int = 0, world: int = 1,
                       seed: Optional[int] = None):
        """Deterministic cross-trainer repartition + shuffle (reference
        GlobalShuffle): every trainer must hold the SAME loaded record
        set (load the full filelist everywhere); each keeps the records
        hashing to its rank, then shuffles locally.  The union across
        ranks is exactly the original set, with a shuffle that does not
        depend on the original per-rank partition."""
        if world > 1:
            def key(i, rec):
                h = hashlib.md5(
                    f"{seed or 0}:{i}:{sorted(rec.items())!r}"
                    .encode()).digest()
                return int.from_bytes(h[:8], "big")
            self._records = [r for i, r in enumerate(self._records)
                             if key(i, r) % world == rank]
        self.local_shuffle(seed)

    def release_memory(self):
        self._records = []

    def get_memory_data_size(self) -> int:
        return len(self._records)

    # ---- batching ------------------------------------------------------
    def _slot_names(self) -> List[str]:
        names = set()
        for r in self._records:
            names.update(r.keys())
        names.discard(self.label_slot)
        return sorted(names)

    def batch_generator(self, batch_size: Optional[int] = None,
                        drop_last: bool = False
                        ) -> Iterator[Dict[str, np.ndarray]]:
        """Yield {slot: array} batches: sparse slots -> (ids [B, T] int64
        padded with -1, '<slot>@len' [B] int64); dense slots -> [B, dim]
        float32; labels -> [B, n_labels] float32."""
        bs = batch_size or self.batch_size
        names = self._slot_names()
        for lo in range(0, len(self._records), bs):
            chunk = self._records[lo:lo + bs]
            if drop_last and len(chunk) < bs:
                return
            out: Dict[str, np.ndarray] = {}
            labels = [r.get(self.label_slot, []) for r in chunk]
            width = max((len(l) for l in labels), default=0)
            lab = np.zeros((len(chunk), max(width, 1)), np.float32)
            for i, l in enumerate(labels):
                lab[i, :len(l)] = l
            out[self.label_slot] = lab
            for name in names:
                if name in self.dense_slots:
                    dim = self.dense_slots[name]
                    arr = np.zeros((len(chunk), dim), np.float32)
                    for i, r in enumerate(chunk):
                        v = r.get(name, [])
                        arr[i, :len(v)] = v
                    out[name] = arr
                else:
                    rows = [r.get(name, []) for r in chunk]
                    t = max((len(x) for x in rows), default=0)
                    ids = np.full((len(chunk), max(t, 1)), -1, np.int64)
                    lens = np.zeros((len(chunk),), np.int64)
                    for i, x in enumerate(rows):
                        ids[i, :len(x)] = x
                        lens[i] = len(x)
                    out[name] = ids
                    out[f"{name}@len"] = lens
            yield out

    def __iter__(self):
        return self.batch_generator()
