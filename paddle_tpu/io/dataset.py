"""Datasets (reference python/paddle/fluid/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__getitem__", self.__class__.__name__))

    def __len__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__len__", self.__class__.__name__))


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__iter__", self.__class__.__name__))

    def __getitem__(self, idx):
        raise RuntimeError(
            "'__getitem__' is not supported on IterableDataset")

    def __len__(self):
        raise RuntimeError("'__len__' is not supported on IterableDataset")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..core.tensor import Tensor
        self.tensors = tensors
        lens = {t.shape[0] if isinstance(t, Tensor) else len(t)
                for t in tensors}
        if len(lens) != 1:
            raise ValueError("all tensors must have the same first dim")

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return t.shape[0] if hasattr(t, "shape") else len(t)


class ComposeDataset(Dataset):
    """Fields of several same-length datasets concatenated per sample."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        lens = {len(d) for d in self.datasets}
        if len(lens) != 1:
            raise ValueError("datasets must share length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum(
            [len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    """reference dataset.py random_split; fractions supported."""
    n = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        counts = [int(np.floor(n * l)) for l in lengths]
        rem = n - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    if sum(lengths) != n:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(n)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out
