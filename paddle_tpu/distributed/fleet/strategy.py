"""DistributedStrategy (reference
python/paddle/distributed/fleet/base/distributed_strategy.py:104 over
proto framework/distributed_strategy.proto:122-166).

Same knob surface, proto replaced by a plain config object (TPU has no
program rewrite passes to configure — the knobs feed the compiled train
step builder instead)."""
from __future__ import annotations

import copy
import json
from typing import Any, Dict

__all__ = ["DistributedStrategy"]

_DEFAULTS: Dict[str, Any] = {
    # mirrored from distributed_strategy.proto (field: default)
    "amp": False,
    "amp_configs": {
        "init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
        "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0, "decr_ratio": 0.5,
        "use_dynamic_loss_scaling": True, "custom_white_list": [],
        "custom_black_list": [], "use_pure_fp16": False,
        "use_bf16": True,  # TPU-native default: bf16 needs no loss scaling
    },
    "recompute": False,
    "recompute_configs": {"checkpoints": [], "policy": "dots"},
    # AQT-style quantization-aware training: route the model's block
    # matmuls through the int8/fp8 fake-quant path (quantized forward,
    # straight-through backward; models expose enable_quantize())
    "qat": False,
    "qat_configs": {"quantize": "int8"},
    "sharding": False,
    "sharding_configs": {"sharding_group_size": 8, "stage": 2,
                         "hybrid_dp": False, "fuse_broadcast_MB": 32.0},
    "pipeline": False,
    "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1,
                         "schedule_mode": "F-then-B"},
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1},
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "sequence_parallel": False,
    "sequence_parallel_configs": {"degree": 1, "mode": "ring"},
    "expert_parallel": False,
    "expert_parallel_configs": {"degree": 1, "capacity_factor": 1.25},
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01,
                     "exclude_from_weight_decay": []},
    "lars": False,
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "adaptive_localsgd": False,
    "adaptive_localsgd_configs": {"init_k_steps": 1, "begin_step": 1},
    "dgc": False,
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
    "a_sync": False,
    "a_sync_configs": {"k_steps": -1},
    "elastic": False,
    "auto": False,
    "fp16_allreduce": False,
    "find_unused_parameters": False,
    "nccl_comm_num": 1,
    "hierarchical_allreduce_inter_nranks": 1,
    "use_hierarchical_allreduce": False,
    "fuse_grad_size_in_MB": 32,
    "last_comm_group_size_MB": 1,
    "fuse_all_reduce_ops": True,
}


class DistributedStrategy:
    def __init__(self):
        self._conf = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        conf = object.__getattribute__(self, "_conf")
        if name in conf:
            return conf[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name == "_conf":
            object.__setattr__(self, name, value)
            return
        if name not in self._conf:
            raise AttributeError(f"unknown strategy field {name!r}")
        cur = self._conf[name]
        if isinstance(cur, dict) and isinstance(value, dict):
            cur.update(value)
        else:
            self._conf[name] = value

    # parity helpers
    def to_dict(self):
        return copy.deepcopy(self._conf)

    def save_to_prototxt(self, path):
        with open(path, "w") as f:
            json.dump(self._conf, f, indent=2, default=str)

    def load_from_prototxt(self, path):
        with open(path) as f:
            self._conf.update(json.load(f))

    def __repr__(self):
        on = [k for k, v in self._conf.items() if v is True]
        return f"DistributedStrategy(enabled={on})"


# hybrid parallel degree helper used by fleet.init(is_collective=True)
def hybrid_degrees(strategy: DistributedStrategy):
    tp = strategy.tensor_parallel_configs.get("tensor_parallel_degree", 1) \
        if strategy.tensor_parallel else 1
    pp = strategy.pipeline_configs.get("accumulate_steps", 1) and \
        strategy.pipeline_configs.get("pp_degree", 1) \
        if strategy.pipeline else 1
    sp = strategy.sequence_parallel_configs.get("degree", 1) \
        if strategy.sequence_parallel else 1
    ep = strategy.expert_parallel_configs.get("degree", 1) \
        if strategy.expert_parallel else 1
    return {"tp": tp or 1, "pp": pp or 1, "sp": sp, "ep": ep}
