"""Fleet: the distributed-training facade.

Reference: python/paddle/distributed/fleet/ (fleet_base.py:63 init /
:594 distributed_optimizer / :1066 minimize; DistributedStrategy proto
distributed_strategy.proto:122; meta-optimizer chain amp→recompute→
sharding→pipeline→graph_execution).

TPU-native: the meta-optimizer program-rewrite chain becomes a strategy
bag consumed by ONE compiled train step: amp = dtype policy, recompute =
jax.checkpoint policy, sharding = opt-state/param sharding specs (ZeRO),
pipeline/tensor/data parallel = mesh axes. `distributed_optimizer`
returns a wrapper that carries the strategy into
paddle_tpu.distributed.spmd.make_train_step (the 'StrategyCompiler').
"""
from .base import (  # noqa: F401
    init, is_first_worker, worker_index, worker_num, is_worker,
    worker_endpoints, server_num, server_index, server_endpoints,
    is_server, barrier_worker, init_worker, init_server, run_server,
    stop_worker, distributed_optimizer, DistributedOptimizer,
    distributed_model, save_persistables, save_inference_model, minimize)
from .strategy import DistributedStrategy  # noqa: F401
from .dgc import DGCMomentum  # noqa: F401
from .role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker  # noqa: F401
