"""Deep Gradient Compression momentum optimizer (eager/DDP path).

Reference: python/paddle/fluid/optimizer.py DGCMomentumOptimizer +
fleet/meta_optimizers/dgc_optimizer.py + paddle/fluid/operators/dgc_op.h.
The DGC algorithm (Lin et al.): per parameter keep two residuals
  u <- m * u + g                (momentum correction)
  v <- v + u                    (gradient accumulation)
select the top-k entries of |v|; transmit ONLY those (k = (1-sparsity)
of the elements), zero them out of both residuals, and apply the summed
sparse gradient with a plain SGD step.  Momentum lives in u — the
optimizer update itself is momentum-free, exactly the reference split.

TPU-native comm: each rank all_gathers its (indices, values) pair —
world * 2k numbers instead of n — and scatter-adds the union locally.
Dense fallbacks: small params (< min_dgc_size, reference uses the same
cutoff idea) and all params before rampup_begin_step use a fused dense
allreduce.

Sparsity rampup (dgc_op.h get_period_sparcity): `sparsity` is a
schedule; step s inside [rampup_begin_step, rampup_begin_step +
rampup_step) indexes the list proportionally, after which the final
entry holds.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...optimizer.optimizer import Optimizer
from .. import env
from ..collective import all_gather, all_reduce, ReduceOp

__all__ = ["DGCMomentum"]


class DGCMomentum(Optimizer):
    _accum_names = ("u", "v")

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, rampup_begin_step=0, rampup_step=1,
                 sparsity: Sequence[float] = (0.999,), min_dgc_size=16384,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip)
        if use_nesterov:
            raise NotImplementedError(
                "DGC with Nesterov momentum is not implemented")
        self._momentum = float(momentum)
        self.rampup_begin_step = int(rampup_begin_step)
        self.rampup_step = max(int(rampup_step), 1)
        self.sparsity: List[float] = list(sparsity)
        self.min_dgc_size = int(min_dgc_size)

    # ---- schedule -------------------------------------------------------
    def current_sparsity(self, step: int) -> float:
        """get_period_sparcity: walk the sparsity list over the rampup
        window, then hold the last value."""
        if step < self.rampup_begin_step:
            return 0.0
        i = (step - self.rampup_begin_step) * len(self.sparsity) \
            // self.rampup_step
        return self.sparsity[min(i, len(self.sparsity) - 1)]

    def _use_dgc(self, p, step: int) -> bool:
        return (step >= self.rampup_begin_step and
                math.prod(p.shape or (1,)) >= self.min_dgc_size)

    # ---- update ---------------------------------------------------------
    def _update(self, p, g, state, lr, step):
        world = env.get_world_size()
        step = int(step)
        if not self._use_dgc(p, step):
            # dense path: plain synchronized momentum (reference keeps
            # the momentum op for non-DGC params)
            if world > 1:
                g = all_reduce(Tensor(g), op=ReduceOp.SUM).data
            v = self._momentum * state["v"] + g
            return p - lr * v, {"u": state["u"], "v": v}

        m = self._momentum
        u = m * state["u"] + g          # momentum correction
        v = state["v"] + u              # local accumulation
        n = math.prod(v.shape)
        sp = self.current_sparsity(step)
        k = max(1, min(n, int(round(n * (1.0 - sp)))))

        flat = v.reshape(-1)
        vals, idx = _topk_abs(flat, k)
        # zero the transmitted entries out of both residuals
        flat_v = flat.at[idx].set(0.0)
        flat_u = u.reshape(-1).at[idx].set(0.0)

        if world > 1:
            all_idx = _as_array(all_gather(idx)).reshape(-1)
            all_vals = _as_array(all_gather(vals)).reshape(-1)
        else:
            all_idx, all_vals = idx, vals
        g_sync = jnp.zeros_like(flat).at[all_idx].add(all_vals)

        new_p = p - lr * g_sync.reshape(p.shape)
        return new_p, {"u": flat_u.reshape(p.shape),
                       "v": flat_v.reshape(p.shape)}


def _topk_abs(flat, k):
    import jax
    vals_abs, idx = jax.lax.top_k(jnp.abs(flat), k)
    return flat[idx], idx


def _as_array(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)
