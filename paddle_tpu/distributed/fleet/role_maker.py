"""Role makers: rank/role discovery from environment.

Reference: python/paddle/distributed/fleet/base/role_maker.py:528
(PaddleCloudRoleMaker — PADDLE_* env contract), :875 (UserDefinedRoleMaker).
The gloo rendezvous (role_maker.py:120-138) is replaced by the JAX
coordinator (env.init_parallel_env)."""
from __future__ import annotations

import os

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def _is_first_worker(self):
        return self._worker_index() == 0

    def _worker_index(self):
        return 0

    def _worker_num(self):
        return 1

    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _server_num(self):
        return 0

    def _server_index(self):
        return 0

    def _get_trainer_endpoints(self):
        return []

    def _get_pserver_endpoints(self):
        return []

    def _barrier(self, comm_world=None):
        from .. import collective
        collective.barrier()

    def _generate_role(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._kwargs = kwargs
        self._generate_role()

    def _generate_role(self):
        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        pseps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = pseps.split(",") if pseps else []
        role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        if self._role == Role.SERVER:
            self._server_id = int(os.environ.get("PADDLE_PORT_ID", "0"))

    def _worker_index(self):
        return self._trainer_id

    def _worker_num(self):
        return self._trainers_num

    def _server_num(self):
        return len(self._server_endpoints)

    def _server_index(self):
        return getattr(self, "_server_id", 0)

    def _get_trainer_endpoints(self):
        return self._trainer_endpoints

    def _get_pserver_endpoints(self):
        return self._server_endpoints


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        self._init_kwargs = kwargs
        super().__init__(is_collective, **kwargs)

    def _generate_role(self):
        kw = self._init_kwargs
        self._trainer_id = kw.get("current_id", 0)
        self._trainers_num = kw.get("worker_num",
                                    len(kw.get("worker_endpoints", [1])))
        self._trainer_endpoints = kw.get("worker_endpoints", [])
        self._server_endpoints = kw.get("server_endpoints", [])
        role = kw.get("role", Role.WORKER)
        self._role = role
