"""Fleet facade (reference fleet/base/fleet_base.py — init:130,
distributed_optimizer:594, minimize:1066).

The reference's minimize() rewrote the program through a chain of meta
optimizers; here DistributedOptimizer carries the strategy and, in eager
mode, applies the pieces that make sense per-step (grad merge, lamb/lars
swap); compiled trainers read the same strategy through
paddle_tpu.distributed.spmd.
"""
from __future__ import annotations

from typing import List, Optional

from .. import env
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase
from .strategy import DistributedStrategy

_role_maker: Optional[RoleMakerBase] = None
_user_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective=False, strategy=None):
    """fleet.init parity (fleet_base.py:130)."""
    global _role_maker, _user_strategy
    _role_maker = role_maker or PaddleCloudRoleMaker(
        is_collective=is_collective)
    _user_strategy = strategy or DistributedStrategy()
    env.init_parallel_env()
    return None


def _rm() -> RoleMakerBase:
    global _role_maker
    if _role_maker is None:
        init()
    return _role_maker


def is_first_worker():
    return _rm()._is_first_worker()


def worker_index():
    return _rm()._worker_index()


def worker_num():
    return _rm()._worker_num()


def is_worker():
    return _rm()._is_worker()


def worker_endpoints(to_string=False):
    eps = _rm()._get_trainer_endpoints()
    return ",".join(eps) if to_string else eps


def server_num():
    return _rm()._server_num()


def server_index():
    return _rm()._server_index()


def server_endpoints(to_string=False):
    eps = _rm()._get_pserver_endpoints()
    return ",".join(eps) if to_string else eps


def is_server():
    return _rm()._is_server()


def barrier_worker():
    _rm()._barrier()


_ps_server = None


def init_worker():
    pass


def init_server(*args, **kwargs):
    """Build this role's PS shard (reference fleet_base.py init_server):
    binds the server endpoint from the role maker; tables are created
    lazily by client ensure_table calls."""
    global _ps_server
    from ..ps import PSServer
    rm = _rm()
    eps = rm._get_pserver_endpoints()
    if not eps:
        raise RuntimeError(
            "init_server: no PADDLE_PSERVERS_IP_PORT_LIST endpoints in "
            "the environment (set by the launcher in PS mode)")
    idx = rm._server_index()
    _ps_server = PSServer(eps[idx], n_workers=max(rm._worker_num(), 1))
    return _ps_server


def run_server():
    """Blocking PS serve loop (reference fleet.run_server).  The shard
    must have been built by init_server()."""
    if _ps_server is None:
        init_server()
    _ps_server.run()


def stop_worker():
    pass


def save_persistables(executor=None, dirname=None, main_program=None,
                      mode=0):
    raise NotImplementedError(
        "use paddle_tpu.save(model.state_dict(), path) or "
        "paddle_tpu.distributed.checkpoint for sharded arrays")


def save_inference_model(*args, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.save to export a compiled inference function")


class DistributedOptimizer:
    """Wraps a user optimizer with the DistributedStrategy (reference
    fleet_base.py:594 distributed_optimizer + the meta-opt chain applied
    in minimize:1066)."""

    def __init__(self, optimizer, strategy: Optional[DistributedStrategy]):
        self.inner_opt = optimizer
        self.user_defined_strategy = strategy or _user_strategy or \
            DistributedStrategy()
        self._grad_merge_count = 0
        self._localsgd_count = 0
        self._swap_large_batch_opt()
        self._swap_dgc_opt()

    def _swap_large_batch_opt(self):
        """lamb/lars strategy flags swap the update rule (reference
        lamb_optimizer.py/lars_optimizer.py meta-opts)."""
        from ... import optimizer as opt_mod
        s = self.user_defined_strategy
        inner = self.inner_opt
        if s.lamb and isinstance(inner, opt_mod.Momentum) is False and \
                not isinstance(inner, opt_mod.Lamb):
            cfg = s.lamb_configs
            self.inner_opt = opt_mod.Lamb(
                learning_rate=inner._lr,
                lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
                parameters=inner._parameters,
                grad_clip=inner._grad_clip)
        elif s.lars and isinstance(inner, opt_mod.Momentum):
            cfg = s.lars_configs
            self.inner_opt = opt_mod.Lars(
                learning_rate=inner._lr,
                momentum=inner._momentum,
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                parameters=inner._parameters,
                grad_clip=inner._grad_clip)

    def _swap_dgc_opt(self):
        """strategy.dgc swaps a Momentum inner optimizer for the DGC
        top-k-compressed one (reference fleet/meta_optimizers/
        dgc_optimizer.py: DGC applies only to Momentum)."""
        from ... import optimizer as opt_mod
        s = self.user_defined_strategy
        inner = self.inner_opt
        if not s.dgc:
            return
        from .dgc import DGCMomentum
        if isinstance(inner, DGCMomentum):
            return
        if not isinstance(inner, opt_mod.Momentum):
            raise NotImplementedError(
                "strategy.dgc requires a Momentum inner optimizer "
                "(reference dgc_optimizer.py has the same constraint)")
        cfg = s.dgc_configs
        self.inner_opt = DGCMomentum(
            learning_rate=inner._lr,
            momentum=inner._momentum,
            parameters=inner._parameters,
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            rampup_step=cfg.get("rampup_step", 1),
            sparsity=cfg.get("sparsity", [0.999]),
            min_dgc_size=cfg.get("min_dgc_size", 16384),
            grad_clip=inner._grad_clip)

    def _localsgd_sync(self):
        """strategy.localsgd (reference fleet/meta_optimizers/
        localsgd_optimizer.py:440): every k_steps, replace each rank's
        params with the cross-rank average — between syncs ranks train
        fully locally (no per-step grad allreduce)."""
        from .. import env as _env
        from ..collective import all_reduce, ReduceOp
        s = self.user_defined_strategy
        if s.adaptive_localsgd and not s.localsgd:
            # adaptive variant (reference adaptive_localsgd_optimizer):
            # the loss-driven k adaptation is simplified to its
            # init_k_steps seed — the sync mechanics are identical
            cfg = s.adaptive_localsgd_configs
            k = int(cfg.get("init_k_steps", 1))
            begin = int(cfg.get("begin_step", 1))
        else:
            k = int(s.localsgd_configs.get("k_steps", 1))
            begin = int(s.localsgd_configs.get("begin_step", 1))
        self._localsgd_count += 1
        if self._localsgd_count < begin or \
                (self._localsgd_count - begin) % max(k, 1) != 0:
            return
        world = _env.get_world_size()
        if world <= 1:
            return
        for p in self.inner_opt._parameters or []:
            red = all_reduce(p.data, op=ReduceOp.SUM)
            p._data = (red / world).astype(p.data.dtype)

    def get_lr(self):
        return self.inner_opt.get_lr()

    def step(self):
        s = self.user_defined_strategy
        if s.gradient_merge:
            k = s.gradient_merge_configs.get("k_steps", 1)
            self._grad_merge_count += 1
            if self._grad_merge_count % k != 0:
                return  # accumulate: grads stay on params
            if s.gradient_merge_configs.get("avg", True):
                for p in self.inner_opt._parameters or []:
                    if p.grad is not None:
                        p.grad._data = p.grad.data / k
        self.inner_opt.step()
        if s.localsgd or s.adaptive_localsgd:
            self._localsgd_sync()
        if s.gradient_merge:
            self.inner_opt.clear_grad()

    def clear_grad(self, *a, **k):
        s = self.user_defined_strategy
        if s.gradient_merge and \
                self._grad_merge_count % s.gradient_merge_configs.get(
                    "k_steps", 1) != 0:
            return  # keep accumulating
        self.inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, []

    def state_dict(self):
        return self.inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self.inner_opt.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self.__dict__["inner_opt"], name)


def distributed_optimizer(optimizer, strategy=None):
    return DistributedOptimizer(optimizer, strategy)


def distributed_model(model, optimizer, loss_fn, mesh=None):
    """Build the compiled trainer from a fleet-configured optimizer — the
    TPU-native endpoint of the reference's fleet.minimize meta-optimizer
    chain (fleet_base.py:1066: strategy -> program rewrite ->
    ParallelExecutor). Here: strategy -> SpmdTrainer (or GPipeTrainer for
    strategy.pipeline via distributed.pipeline) as ONE XLA executable.

    Returns an SpmdTrainer; drive it with trainer.train_step(x, y).
    """
    from ..mesh import default_mesh
    from ..spmd import SpmdTrainer
    strategy = getattr(optimizer, "user_defined_strategy", None) or \
        _user_strategy
    inner = getattr(optimizer, "inner_opt", optimizer)
    return SpmdTrainer(model, inner, loss_fn,
                       mesh=mesh or default_mesh(), strategy=strategy)


def minimize(loss, **kwargs):
    raise RuntimeError("call fleet.distributed_optimizer(...).minimize")
