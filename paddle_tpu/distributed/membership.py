"""Slice membership: heartbeat failure detection for the DCN tier.

The multi-slice mesh (mesh.py's ``dcn`` axis) groups devices into
slices that fail independently — a whole slice preempted or its DCN
links dead is the failure unit, not a single chip.  This module is the
control plane for that tier:

``SliceMembership``
    One heartbeat record per slice over a pluggable transport.  The
    file transport touches ``slice.<id>`` files (mtime = last beat)
    under ``PADDLE_TPU_SLICE_HB_DIR`` — the same idiom as the
    launcher's per-rank ``hb.<rank>`` files, and the format README
    documents — so any host on shared storage sees every slice's
    health.  The in-memory callback transport backs tests and the
    single-process virtual-slice harness.  ``poll()`` is the failure
    detector: a slice whose last beat is older than ``timeout_s``
    transitions to dead exactly once, emitting a membership-change
    event into the flight recorder, the metrics registry, and to any
    ``on_change`` listener (SpmdTrainer reacts by re-forming the mesh
    in memory — see spmd.reform_mesh).

``DcnCollectiveGuard``
    Timeout + bounded retry with exponential backoff and jitter around
    cross-slice work — the PADDLE_TPU_FS_RETRIES posture (framework/
    fs.py) lifted to comms.  A persistently dead peer escalates into a
    membership change (``SliceLostError``) instead of hanging until
    the stall watchdog declares the whole loop dead; backoff sleeps
    are chunked around an ``on_beat`` callback so the watchdog keeps
    getting fed while the guard is the one doing the waiting.

Env knobs: PADDLE_TPU_SLICE_HB_DIR, PADDLE_TPU_SLICE_HB_TIMEOUT_S
(default 5), PADDLE_TPU_DCN_RETRIES (default 3),
PADDLE_TPU_DCN_TIMEOUT_S (default 10), plus the fault points
PADDLE_FAULT_SLICE_DOWN / PADDLE_FAULT_DCN_DELAY_MS (testing/faults).
"""
from __future__ import annotations

import os
import random
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

__all__ = ["SliceMembership", "FileTransport", "CallbackTransport",
           "DcnCollectiveGuard", "SliceLostError",
           "DEFAULT_SLICE_TIMEOUT_S"]

DEFAULT_SLICE_TIMEOUT_S = 5.0


class SliceLostError(RuntimeError):
    """A DCN peer stayed dead through the guard's full retry budget;
    carries the membership-change event the escalation produced."""

    def __init__(self, msg: str, slice_id: Optional[int] = None,
                 event: Optional[dict] = None):
        super().__init__(msg)
        self.slice_id = slice_id
        self.event = event


class CallbackTransport:
    """In-memory beat store — tests and the single-process
    virtual-slice harness (one process hosting every slice)."""

    def __init__(self):
        self._beats: Dict[int, float] = {}
        self._lock = threading.Lock()

    def beat(self, slice_id: int, now: float):
        with self._lock:
            self._beats[int(slice_id)] = float(now)

    def last_beats(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._beats)


class FileTransport:
    """File-backed beats: ``slice.<id>`` under `directory`, mtime =
    last beat.  Works across processes/hosts on shared storage; pair
    with a wall clock (time.time), which is what SliceMembership
    defaults to."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, slice_id: int) -> str:
        return os.path.join(self.directory, f"slice.{int(slice_id)}")

    def beat(self, slice_id: int, now: float):
        p = self._path(slice_id)
        try:
            with open(p, "a"):
                pass
            os.utime(p, (now, now))
        except OSError:
            pass  # a transient beat-write failure is not a death

    def last_beats(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for n in names:
            if not n.startswith("slice."):
                continue
            try:
                out[int(n[len("slice."):])] = os.path.getmtime(
                    os.path.join(self.directory, n))
            except (ValueError, OSError):
                continue
        return out


class SliceMembership:
    """Heartbeat registry over the mesh's DCN slices.

    Live slices beat every train step; ``poll()`` flags slices whose
    last beat is older than ``timeout_s`` and returns one membership
    event per alive→dead transition.  Slice ids are the ORIGINAL
    numbering for the life of the object — a reform renumbers mesh
    rows, not membership ids.
    """

    def __init__(self, n_slices: int, slice_id: int = 0, transport=None,
                 timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        if timeout_s is None:
            timeout_s = float(os.environ.get(
                "PADDLE_TPU_SLICE_HB_TIMEOUT_S", DEFAULT_SLICE_TIMEOUT_S))
        self.n_slices = int(n_slices)
        self.slice_id = int(slice_id)
        self.timeout_s = float(timeout_s)
        self.transport = transport if transport is not None \
            else self._default_transport()
        self.clock = clock
        self._dead: set = set()
        self._events: List[dict] = []
        self._listeners: List[Callable[[dict], None]] = []
        self._lock = threading.Lock()
        # seed every slice as alive NOW: a registry created mid-run must
        # not declare peers dead before their first beat can land
        now = self.clock()
        for s in range(self.n_slices):
            self.transport.beat(s, now)

    @staticmethod
    def _default_transport():
        d = os.environ.get("PADDLE_TPU_SLICE_HB_DIR")
        return FileTransport(d) if d else CallbackTransport()

    def on_change(self, fn: Callable[[dict], None]):
        self._listeners.append(fn)
        return fn

    # ---- beating ------------------------------------------------------
    def beat(self, slice_id: Optional[int] = None,
             step: Optional[int] = None) -> bool:
        """Record a heartbeat for `slice_id` (default: own slice).
        Honors PADDLE_FAULT_SLICE_DOWN when `step` is given: the armed
        slice's beats are swallowed from the armed step on, so the
        failure detector sees a real growing staleness window."""
        sid = self.slice_id if slice_id is None else int(slice_id)
        if step is not None:
            from ..testing import faults as _faults
            if _faults.slice_is_down(sid, step):
                return False
        self.transport.beat(sid, self.clock())
        return True

    def beat_all(self, step: Optional[int] = None):
        """Beat every surviving slice — the single-process
        virtual-slice harness, where one process IS all slices.  Real
        multi-host deployments call ``beat()`` from each slice's own
        process instead."""
        for s in range(self.n_slices):
            if s not in self._dead:
                self.beat(s, step=step)

    # ---- detection ----------------------------------------------------
    def ages(self, now: Optional[float] = None) -> Dict[int, float]:
        """Seconds since each slice's last beat (None = never seen)."""
        now = self.clock() if now is None else now
        beats = self.transport.last_beats()
        out: Dict[int, float] = {}
        for s in range(self.n_slices):
            last = beats.get(s)
            out[s] = float("inf") if last is None else max(now - last, 0.0)
        return out

    def dead_slices(self) -> set:
        return set(self._dead)

    def alive_slices(self) -> List[int]:
        return [s for s in range(self.n_slices) if s not in self._dead]

    def declare_dead(self, slice_id: int,
                     reason: str = "escalation") -> Optional[dict]:
        """Force a membership change — the DCN guard's escalation path
        (retries exhausted before the heartbeat timeout elapsed).
        Idempotent: an already-dead slice returns None."""
        with self._lock:
            if slice_id in self._dead:
                return None
            self._dead.add(int(slice_id))
            ev = {"kind": "slice_lost", "slice": int(slice_id),
                  "reason": reason, "wall": time.time(),
                  "alive": [s for s in range(self.n_slices)
                            if s not in self._dead]}
            self._events.append(ev)
        try:
            from ..observability import flightrec as _flightrec
            from ..observability import metrics as _metrics
            _metrics.counter("slice_lost_total",
                             "DCN slices declared dead").inc()
            _flightrec.note_event("membership_change", slice=int(slice_id),
                                  reason=reason, alive=ev["alive"])
        except Exception:
            pass
        for fn in list(self._listeners):
            try:
                fn(ev)
            except Exception:
                pass
        return ev

    def poll(self, now: Optional[float] = None) -> List[dict]:
        """Failure-detection tick: update the per-slice age gauges and
        return the membership events for freshly-dead slices (heartbeat
        age past ``timeout_s``), once per transition."""
        ages = self.ages(now)
        try:
            from ..observability import metrics as _metrics
            g = _metrics.gauge("slice_heartbeat_age_s",
                               "seconds since a DCN slice's last heartbeat",
                               labels=("slice",))
            for s, age in ages.items():
                g.labels(slice=str(s)).set(round(min(age, 1e9), 3))
        except Exception:
            pass
        out: List[dict] = []
        for s, age in ages.items():
            if age > self.timeout_s and s not in self._dead:
                ev = self.declare_dead(
                    s, reason=f"heartbeat_timeout age={age:.3f}s")
                if ev is not None:
                    out.append(ev)
        return out

    @property
    def events(self) -> List[dict]:
        return list(self._events)

    def stats(self) -> dict:
        ages = self.ages()
        return {
            "n_slices": self.n_slices,
            "dead": sorted(self._dead),
            "timeout_s": self.timeout_s,
            "heartbeat_ages": {
                s: (round(a, 3) if a != float("inf") else None)
                for s, a in ages.items()},
        }


class DcnCollectiveGuard:
    """Timeout + bounded-retry wrapper for cross-slice (DCN) work.

    ``run(fn, peer_slice=...)`` dispatches fn with: the injected
    slow-DCN delay (PADDLE_FAULT_DCN_DELAY_MS) applied first like real
    cross-DC latency; retries on transient comm errors (TimeoutError /
    OSError, which covers InjectedFault) with exponential backoff and
    deterministic jitter; a per-attempt deadline — an attempt that
    finishes but blows ``timeout_s`` is recorded as slow (a doctor
    signal), not failed; and escalation — retries exhausted turns into
    ``membership.declare_dead(peer_slice)`` + ``SliceLostError``
    instead of an indefinite hang.  Backoff sleeps are chunked around
    ``on_beat`` so the caller's stall watchdog stays fed and the guard
    escalates before the watchdog fires.
    """

    RETRYABLE = (TimeoutError, OSError)

    def __init__(self, membership: Optional[SliceMembership] = None,
                 retries: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 backoff_base_ms: float = 50.0,
                 backoff_max_ms: float = 2000.0,
                 jitter: float = 0.25,
                 on_beat: Optional[Callable[[], None]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if retries is None:
            retries = int(os.environ.get("PADDLE_TPU_DCN_RETRIES", "3"))
        if timeout_s is None:
            timeout_s = float(os.environ.get("PADDLE_TPU_DCN_TIMEOUT_S",
                                             "10"))
        self.membership = membership
        self.retries = max(1, int(retries))
        self.timeout_s = float(timeout_s)
        self.backoff_base_ms = float(backoff_base_ms)
        self.backoff_max_ms = float(backoff_max_ms)
        self.jitter = float(jitter)
        self.on_beat = on_beat
        self.sleep = sleep
        self.retries_used = 0
        self.escalations = 0
        self.slow_dispatches = 0

    def _beat(self):
        if self.on_beat is not None:
            try:
                self.on_beat()
            except Exception:
                pass

    def _backoff(self, attempt: int, label: str):
        delay = min(self.backoff_max_ms,
                    self.backoff_base_ms * (2 ** attempt)) / 1e3
        # deterministic jitter: seeded per (label, attempt) so tests
        # reproduce exactly while distinct collectives still desync
        r = random.Random(zlib.crc32(f"{label}:{attempt}".encode()))
        delay *= 1.0 + self.jitter * r.random()
        end = time.monotonic() + delay
        while True:
            self._beat()  # keep the stall watchdog fed through the wait
            remaining = end - time.monotonic()
            if remaining <= 0:
                break
            self.sleep(min(remaining, 0.25))

    def run(self, fn: Callable, *args, peer_slice: Optional[int] = None,
            label: str = "dcn-collective", **kwargs):
        from ..testing import faults as _faults
        try:
            from ..observability import flightrec as _flightrec
        except Exception:  # pragma: no cover
            _flightrec = None
        last: Optional[BaseException] = None
        for attempt in range(self.retries):
            self._beat()
            _faults.maybe_delay_dcn()
            t0 = time.monotonic()
            try:
                out = fn(*args, **kwargs)
            except self.RETRYABLE as e:
                last = e
                self.retries_used += 1
                if _flightrec is not None:
                    _flightrec.note_event(
                        "dcn_retry", label=label, attempt=attempt + 1,
                        peer_slice=peer_slice,
                        error=f"{type(e).__name__}: {str(e)[:120]}")
                if attempt + 1 < self.retries:
                    self._backoff(attempt, label)
                continue
            dt = time.monotonic() - t0
            if dt > self.timeout_s:
                # completed but blew the deadline: a slow DCN is a
                # doctor signal, not a failure
                self.slow_dispatches += 1
                if _flightrec is not None:
                    _flightrec.note_event("dcn_slow", label=label,
                                          dt_s=round(dt, 3))
            return out
        # retry budget exhausted: escalate to a membership change so
        # the trainer re-forms the mesh instead of hanging on a dead
        # peer until the watchdog kills the whole run
        self.escalations += 1
        try:
            from ..observability import metrics as _metrics
            _metrics.counter("dcn_guard_escalations_total",
                             "DCN guard retry budgets exhausted").inc()
        except Exception:
            pass
        ev = None
        if self.membership is not None and peer_slice is not None:
            ev = self.membership.declare_dead(
                peer_slice, reason=f"dcn_guard:{label}")
        raise SliceLostError(
            f"DCN collective {label!r} failed after {self.retries} "
            f"attempts ({type(last).__name__ if last else '?'}: {last}); "
            f"peer slice {peer_slice} escalated to membership change",
            slice_id=peer_slice, event=ev) from last

    def stats(self) -> dict:
        return {"retries": self.retries, "timeout_s": self.timeout_s,
                "retries_used": self.retries_used,
                "escalations": self.escalations,
                "slow_dispatches": self.slow_dispatches}
