"""Trainer checkpoint / auto-resume.

Reference: fluid/incubate/checkpoint/auto_checkpoint.py:71
(AutoCheckpointChecker / train_epoch_range: periodic save of
persistables + optimizer accumulators + epoch no, auto-restore on
restart) and fleet.save_persistables; optimizer state in the reference
lives in scope vars named `param@accumulator`, so checkpoint = save
persistable vars.

TPU-native: the compiled trainers own sharded device arrays; checkpoint
= host-gather the pytrees (numpy) + a small metadata dict, restore =
device_put each leaf back with its recorded NamedSharding. The
shardings themselves are NOT stored, they come from the rebuilt
trainer, so a checkpoint written on one mesh layout restores onto
another (e.g. dp8 -> dp4) as long as the model matches.

Two on-disk formats:
- legacy single file: one pickle written through fs.open_for_write
  (atomic tmp+rename, now fsync'd);
- manifest directory (Check-N-Run-style verified checkpoints): the
  pickle payload plus MANIFEST.json carrying a sha256 + size per entry,
  written LAST inside a `<name>.tmp` staging dir that is renamed into
  place — so a checkpoint directory that exists at its final name
  always has its manifest, and a manifest that validates proves the
  payload is the exact bytes the writer produced. Truncation, partial
  upload, or bitrot all fail validation and resume falls back to the
  previous valid snapshot instead of crashing.

Manifest/state version 2 (elastic cross-topology restore): the state
dict additionally records the writer's LOGICAL topology — mesh axis
names + sizes and, per saved tree, each leaf's partition spec (axis
names and partitioned dims, never device ids) — and MANIFEST.json
mirrors it (``version: 2``, ``mesh_axes``, per-leaf ``leaves`` entries
with global shape/dtype/spec).  Because every leaf is stored as its
GLOBAL host array, a checkpoint is a topology-free artifact:
``restore_trainer`` rebuilds each leaf against the LIVE trainer's
``NamedSharding`` via ``jax.make_array_from_callback``, so a dp=8 run
resumes on dp=4, a ZeRO-3 stage-3 shard set repartitions onto the new
dp extent, and a pp=4 pipeline's stacked layer slabs re-split over
pp=2 — no resharding pass over the files, the shapes never changed.
Version-1 states (no topology record) load unchanged on any mesh whose
global shapes match, exactly as before.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
from typing import Optional

import numpy as np

import jax

__all__ = ["save_trainer", "load_trainer", "latest_checkpoint",
           "snapshot_trainer", "restore_trainer", "write_checkpoint",
           "read_checkpoint", "validate_checkpoint", "read_manifest",
           "checkpoint_candidates", "gc_stale_tmps", "state_mesh_axes"]

_FORMAT = "paddle_tpu_trainer_ckpt_v1"
_MANIFEST_FORMAT = "paddle_tpu_ckpt_manifest_v1"
_MANIFEST = "MANIFEST.json"
_STATE_ENTRY = "state.pdtrainer"
# state/manifest layout version: 2 = + mesh_axes / per-leaf sharding
# specs (topology-free elastic restore); 1 = the PR-2 layout
_STATE_VERSION = 2


def _to_host(tree):
    """Device -> host snapshot that OWNS its memory.

    np.asarray on a CPU-backend jax array is a zero-copy view into the
    device buffer; once the next donated train step reuses that buffer
    the 'snapshot' silently tracks the live (possibly NaN-poisoned)
    params. Anything captured for later use — async checkpoint payloads,
    rollback snapshots — must copy when numpy hands back a view (base
    is None exactly when the conversion already copied, e.g. on TPU)."""
    def conv(a):
        out = np.asarray(a)
        if out.base is not None:
            out = out.copy()
        return out
    return jax.tree_util.tree_map(conv, tree)


# ---------------------------------------------------------------------------
# logical topology metadata (manifest/state v2)
# ---------------------------------------------------------------------------
def _spec_to_meta(sharding) -> Optional[list]:
    """NamedSharding -> JSON-able per-dim spec: each entry is None
    (replicated), an axis name, or a list of axis names.  Device ids
    never appear — the spec is LOGICAL, that is what makes the record
    valid on a different topology."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out = []
    for e in tuple(spec):
        out.append(list(e) if isinstance(e, tuple) else e)
    return out


def mesh_axes_of(mesh) -> dict:
    """{axis name: size} for a jax Mesh (insertion-ordered)."""
    return {str(n): int(mesh.shape[n]) for n in mesh.axis_names}


def state_mesh_axes(state: dict) -> Optional[dict]:
    """The topology a v2 state was written on, or None (legacy v1)."""
    axes = state.get("mesh_axes")
    return dict(axes) if isinstance(axes, dict) else None


def _trainer_sharding_trees(trainer) -> dict:
    """{state key: sharding pytree} for every tree snapshot_trainer
    saves — the source of the per-leaf spec metadata."""
    trees = {
        "params": getattr(trainer, "_param_shardings", None),
        "opt_state": getattr(trainer, "_opt_shardings", None),
    }
    if getattr(trainer, "buffers", None):
        trees["buffers"] = getattr(trainer, "_buffer_shardings", None)
    if getattr(trainer, "_grad_buf", None) is not None:
        trees["grad_buf"] = getattr(trainer, "_grad_shardings", None)
    if getattr(trainer, "_scaler_state", None) is not None:
        trees["scaler"] = getattr(trainer, "_scaler_shardings", None)
    if getattr(trainer, "_anomaly_state", None) is not None:
        trees["anomaly"] = getattr(trainer, "_anomaly_shardings", None)
    return {k: v for k, v in trees.items() if v is not None}


def _topology_record(trainer) -> dict:
    """mesh_axes + per-tree/per-leaf partition specs for the state dict
    (pickled whole) and, flattened, for MANIFEST.json."""
    specs = {}
    for key, tree in _trainer_sharding_trees(trainer).items():
        specs[key] = jax.tree_util.tree_map(
            _spec_to_meta, tree,
            is_leaf=lambda s: hasattr(s, "spec") or s is None)
    return {"mesh_axes": mesh_axes_of(trainer.mesh),
            "sharding_specs": specs}


def _manifest_leaves(state: dict) -> dict:
    """Per-leaf {path: {shape, dtype, spec}} manifest metadata for the
    v2 state's array trees — human- and tool-readable without
    unpickling the payload."""
    out = {}
    specs = state.get("sharding_specs") or {}
    for key in ("params", "opt_state", "buffers", "grad_buf",
                "scaler", "anomaly"):
        if key not in state:
            continue
        leaves = jax.tree_util.tree_flatten_with_path(state[key])[0]
        spec_tree = specs.get(key)
        is_spec = lambda x: x is None or (  # noqa: E731
            isinstance(x, list) and all(
                e is None or isinstance(e, (str, list)) for e in x))
        spec_leaves = None
        if spec_tree is not None:
            spec_leaves = jax.tree_util.tree_flatten(
                spec_tree, is_leaf=is_spec)[0]
            if len(spec_leaves) != len(leaves):
                spec_leaves = None  # tree drift: keep manifest honest
        for i, (path, leaf) in enumerate(leaves):
            name = key + jax.tree_util.keystr(path)
            ent = {"shape": [int(d) for d in np.shape(leaf)],
                   "dtype": str(np.asarray(leaf).dtype)}
            if spec_leaves is not None:
                ent["spec"] = spec_leaves[i]
            out[name] = ent
    return out


# ---------------------------------------------------------------------------
# trainer state <-> host pytree
# ---------------------------------------------------------------------------
def snapshot_trainer(trainer, extra: Optional[dict] = None) -> dict:
    """Device -> host snapshot of a trainer's full training state
    (params + optimizer state + step count + LR-scheduler state
    [+ gradient-merge buffer, fp16 scaler, anomaly counters]), plus the
    v2 topology record (mesh axes + per-leaf logical sharding specs)
    that makes the checkpoint restorable on a different mesh.

    This is the only part of a save that must run on the training
    thread (it synchronizes with the device); serialization and disk
    I/O can happen on a background thread (resilience.CheckpointManager).
    """
    from ..optimizer.lr import LRScheduler
    # park the trainer's stall watchdog for the duration of the save
    # (and any post-training tail): a slow final checkpoint is not a
    # wedged step loop, and the next train_step re-beats it
    wd = getattr(trainer, "watchdog", None)
    if wd is not None:
        wd.idle()
    state = {
        "format": _FORMAT,
        "version": _STATE_VERSION,
        "step_count": trainer._step_count,
        "params": _to_host(trainer.params),
        "opt_state": _to_host(trainer.opt_state),
        "extra": extra or {},
    }
    # v2 topology record: LOGICAL mesh + per-leaf partition specs.  A
    # trainer without a mesh (hand-rolled test double) degrades to a
    # v1-equivalent state that restores on an identical layout only.
    if getattr(trainer, "mesh", None) is not None:
        state.update(_topology_record(trainer))
    if getattr(trainer, "buffers", None):
        state["buffers"] = _to_host(trainer.buffers)
    if getattr(trainer, "_grad_buf", None) is not None:
        state["grad_buf"] = _to_host(trainer._grad_buf)
    if getattr(trainer, "_scaler_state", None) is not None:
        state["scaler"] = _to_host(trainer._scaler_state)
    if getattr(trainer, "_anomaly_state", None) is not None:
        state["anomaly"] = _to_host(trainer._anomaly_state)
    lr = getattr(trainer.optimizer, "_lr", None)
    if isinstance(lr, LRScheduler):
        state["lr_scheduler"] = lr.state_dict()
    return state


def _place_leaf(host_arr, dtype, sharding):
    """Rebuild one GLOBAL host array on the live mesh under `sharding`.

    jax.make_array_from_callback hands each addressable device exactly
    its shard (the resharding primitive: the callback's index is
    computed from the NEW NamedSharding, whatever topology wrote the
    array).  Each shard is materialized as an OWNED copy — on the CPU
    backend a device_put of a numpy view can be zero-copy, and buffers
    aliased into later-donated trainer state are the PR-2 hazard."""
    h = np.asarray(host_arr).astype(dtype, copy=False)
    if h.ndim == 0:
        # scalars: the callback indexing protocol is pointless overhead
        return jax.device_put(h.copy(), sharding)
    try:
        return jax.make_array_from_callback(
            h.shape, sharding, lambda idx: np.array(h[idx]))
    except Exception:
        # very old jax / exotic sharding: whole-array placement still
        # reshards correctly, just without per-shard construction
        return jax.device_put(np.array(h), sharding)


def _restore_tree(host_tree, live_tree, shardings):
    """Rebuild each host leaf with the LIVE trainer's sharding,
    verifying structure + global shapes against the live state.  The
    shardings (and the mesh under them) come from the trainer, so the
    checkpoint's topology never constrains the restore."""
    h_leaves, h_def = jax.tree_util.tree_flatten(host_tree)
    l_leaves, l_def = jax.tree_util.tree_flatten(live_tree)
    if h_def != l_def:
        raise ValueError(
            f"checkpoint structure mismatch: {h_def} vs trainer {l_def}")
    s_leaves = jax.tree_util.tree_flatten(shardings)[0]
    out = []
    for h, l, s in zip(h_leaves, l_leaves, s_leaves):
        if tuple(h.shape) != tuple(l.shape):
            raise ValueError(
                f"checkpoint leaf shape {h.shape} != trainer {l.shape}")
        out.append(_place_leaf(h, l.dtype, s))
    return jax.tree_util.tree_unflatten(l_def, out)


def restore_trainer(trainer, state: dict,
                    elastic: Optional[bool] = None) -> dict:
    """Apply a snapshot_trainer() state dict to a (re)built trainer;
    shardings come from the trainer, so the mesh layout may differ from
    the one that wrote the checkpoint (elastic shrink/grow restore).
    Returns the 'extra' dict.

    `elastic` gates CROSS-TOPOLOGY restores (v2 states record their
    mesh): None consults trainer.resume_elastic (default: allowed),
    False raises on a mesh mismatch instead of silently resharding —
    the strict mode for jobs whose numerics must be bitwise-stable.
    The outcome is recorded on the trainer (`_last_restore_info`,
    `_reshard_restores`) for stats/telemetry."""
    from ..optimizer.lr import LRScheduler
    if state.get("format") != _FORMAT:
        raise ValueError(f"state is not a {_FORMAT} checkpoint")
    saved_axes = state_mesh_axes(state)
    live_axes = mesh_axes_of(trainer.mesh) \
        if getattr(trainer, "mesh", None) is not None else None
    resharded = (saved_axes is not None and live_axes is not None
                 and saved_axes != live_axes)
    if resharded:
        if elastic is None:
            elastic = getattr(trainer, "resume_elastic", None)
        if elastic is False:
            raise ValueError(
                f"checkpoint was written on mesh {saved_axes} but the "
                f"live mesh is {live_axes}; pass resume_elastic=True "
                f"(or elastic=True) to reshard onto the new topology")
    trainer._last_restore_info = {
        "resharded": resharded, "saved_mesh_axes": saved_axes,
        "mesh_axes": live_axes,
        "version": int(state.get("version", 1)),
    }
    if resharded:
        trainer._reshard_restores = getattr(
            trainer, "_reshard_restores", 0) + 1
    trainer.params = _restore_tree(state["params"], trainer.params,
                                   trainer._param_shardings)
    trainer.opt_state = _restore_tree(state["opt_state"],
                                      trainer.opt_state,
                                      trainer._opt_shardings)
    if "buffers" in state and getattr(trainer, "buffers", None):
        trainer.buffers = _restore_tree(state["buffers"], trainer.buffers,
                                        trainer._buffer_shardings)
    if "grad_buf" in state and getattr(trainer, "_grad_buf", None) \
            is not None:
        trainer._grad_buf = _restore_tree(
            state["grad_buf"], trainer._grad_buf, trainer._grad_shardings)
    if "scaler" in state and getattr(trainer, "_scaler_state", None) \
            is not None:
        trainer._scaler_state = _restore_tree(
            state["scaler"], trainer._scaler_state,
            trainer._scaler_shardings)
    if getattr(trainer, "_anomaly_state", None) is not None:
        if "anomaly" in state:
            trainer._anomaly_state = _restore_tree(
                state["anomaly"], trainer._anomaly_state,
                trainer._anomaly_shardings)
        else:
            # checkpoint written without anomaly state (raise-policy or
            # pre-resilience run): every recorded step was applied, so
            # the optimizer-visible counter equals the global count —
            # leaving t=0 would rewind Adam bias correction to step 1
            import jax.numpy as jnp
            trainer._anomaly_state = {
                "t": jax.device_put(
                    jnp.asarray(int(state["step_count"]), jnp.int32),
                    trainer._anomaly_shardings["t"]),
                "skipped": jax.device_put(
                    jnp.asarray(0, jnp.int32),
                    trainer._anomaly_shardings["skipped"]),
            }
    trainer._step_count = int(state["step_count"])
    ksteps = getattr(trainer, "k_steps", 1)
    trainer.optimizer._step_count = trainer._step_count // max(ksteps, 1)
    lr = getattr(trainer.optimizer, "_lr", None)
    if isinstance(lr, LRScheduler) and "lr_scheduler" in state:
        lr.set_state_dict(state["lr_scheduler"])
    from ..observability import flightrec as _flightrec
    _flightrec.note_event("checkpoint_restore",
                          step=trainer._step_count,
                          resharded=resharded)
    return state.get("extra", {})


# ---------------------------------------------------------------------------
# on-disk formats
# ---------------------------------------------------------------------------
def _rm(path: str):
    if os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)


def checkpoint_candidates(directory: str, prefix: str = "ckpt-"):
    """Committed `{prefix}{int step}` entries as (step, path), newest
    first — the single definition of 'what counts as a checkpoint'
    shared by latest_checkpoint and resilience.CheckpointManager."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        if not name.startswith(prefix) or name.endswith(".tmp"):
            continue
        try:
            step = int(name[len(prefix):])
        except ValueError:
            continue
        out.append((step, os.path.join(directory, name)))
    return sorted(out, reverse=True)


def gc_stale_tmps(directory: str, prefix: str = "ckpt-"):
    """Remove `.tmp` staging orphans left by crashed saves. Call only
    when no writer is active on the directory (resume time / after a
    commit in the single-writer CheckpointManager)."""
    if not os.path.isdir(directory):
        return
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(".tmp"):
            try:
                _rm(os.path.join(directory, name))
            except OSError:
                pass


def write_checkpoint(state: dict, path: str) -> str:
    """Commit `state` as a manifest directory at `path`.

    Protocol: serialize into `path + ".tmp"`, fsync the payload, write
    MANIFEST.json (checksums) LAST, fsync it, then atomically rename the
    staging dir to `path`. A crash at any point leaves either the old
    checkpoint or a `.tmp` orphan (GC'd by latest_checkpoint /
    CheckpointManager), never a half-committed final directory.
    """
    from ..framework.fs import fsync_file, _fsync_dir
    from ..testing import faults as _faults
    tmp = path + ".tmp"
    _rm(tmp)
    os.makedirs(tmp)
    payload = pickle.dumps(state, protocol=4)
    # fault point (PADDLE_FAULT_CKPT_TRUNCATE): die mid-commit leaving
    # a PARTIAL shard at the final path — the manifest records the full
    # payload, so the committed dir exists but fails validation, which
    # is exactly what resume's fallback walk must survive
    truncate_and_die = _faults.ckpt_truncate_commit()
    body = payload if not truncate_and_die \
        else payload[:max(1, len(payload) // 2)]
    with open(os.path.join(tmp, _STATE_ENTRY), "wb") as f:
        f.write(body)
        fsync_file(f)
    manifest = {
        "format": _MANIFEST_FORMAT,
        "version": int(state.get("version", 1)),
        "step": int(state.get("step_count", -1)),
        "entries": {_STATE_ENTRY: {
            "sha256": hashlib.sha256(payload).hexdigest(),
            "size": len(payload),
        }},
    }
    if state_mesh_axes(state) is not None:
        manifest["mesh_axes"] = state_mesh_axes(state)
        manifest["leaves"] = _manifest_leaves(state)
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump(manifest, f)
        fsync_file(f)
    if truncate_and_die:
        _rm(path)
        os.rename(tmp, path)   # committed-looking, but the shard is cut
        _faults.flightrec_dump("ckpt_truncate")  # black box first
        os._exit(137)          # SIGKILL-style death, no cleanup
    if os.path.exists(path):
        # re-save of the same step: rename the old one aside first so
        # the no-checkpoint window is two rename syscalls, not a
        # multi-GB delete; the ".old.tmp" suffix makes a crash-orphaned
        # copy invisible to candidates and GC'd like any staging dir
        old = path + ".old.tmp"
        _rm(old)
        os.rename(path, old)
        os.rename(tmp, path)
        _rm(old)
    else:
        os.rename(tmp, path)
    _fsync_dir(os.path.dirname(path))
    return path


def validate_checkpoint(path: str) -> bool:
    """Cheap integrity check without a full restore.

    Manifest directories verify size + sha256 of every entry against
    MANIFEST.json; legacy single-file checkpoints get a pickle-header
    sniff (first byte \\x80) — and hapi's eager-mode JSON markers (first
    byte '{') also pass, since both are valid resume candidates."""
    if os.path.isdir(path):
        try:
            with open(os.path.join(path, _MANIFEST)) as f:
                manifest = json.load(f)
            if manifest.get("format") != _MANIFEST_FORMAT:
                return False
            for name, meta in manifest.get("entries", {}).items():
                p = os.path.join(path, name)
                if not os.path.isfile(p) or \
                        os.path.getsize(p) != int(meta["size"]):
                    return False
                h = hashlib.sha256()
                with open(p, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
                if h.hexdigest() != meta["sha256"]:
                    return False
            return True
        except (OSError, ValueError, KeyError, TypeError):
            return False
    try:
        if os.path.getsize(path) == 0:
            return False
        with open(path, "rb") as f:
            head = f.read(1)
        return head in (b"\x80", b"{")
    except OSError:
        return False


def read_manifest(path: str) -> Optional[dict]:
    """MANIFEST.json of a directory checkpoint (None for legacy single
    files / missing manifest).  The cheap way to learn a checkpoint's
    step, version and — v2 — the mesh it was written on, without
    unpickling a multi-GB payload."""
    if not os.path.isdir(path):
        return None
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_checkpoint(path: str) -> dict:
    """Load a checkpoint state dict from either format, verifying the
    manifest for directory checkpoints (raises ValueError on corruption
    — callers that want fallback catch it and try the next candidate)."""
    if os.path.isdir(path):
        if not validate_checkpoint(path):
            raise ValueError(
                f"checkpoint {path} failed manifest/checksum validation "
                f"(truncated or corrupt)")
        with open(os.path.join(path, _STATE_ENTRY), "rb") as f:
            return pickle.load(f)
    from ..framework.fs import open_for_read
    with open_for_read(path, "rb") as f:
        return pickle.load(f)


# ---------------------------------------------------------------------------
# public single-call API (SpmdTrainer.save/load, GPipeTrainer.save/load)
# ---------------------------------------------------------------------------
def save_trainer(trainer, path: str, extra: Optional[dict] = None,
                 manifest: bool = False) -> str:
    """Persist a trainer's full training state. manifest=True writes the
    integrity-checked directory format (local paths only); the default
    stays the legacy single pickle for drop-in compatibility (also the
    only format that rides hdfs:// paths)."""
    import time as _time
    from ..observability import metrics as _metrics
    t0 = _time.perf_counter()
    state = snapshot_trainer(trainer, extra=extra)
    if manifest:
        out = write_checkpoint(state, path)
    else:
        # fs backend (reference framework/io/fs.cc): local paths write
        # fsync + tmp+rename (atomic — a killed save never corrupts),
        # hdfs:// paths stage locally and upload
        from ..framework.fs import open_for_write
        with open_for_write(path, "wb") as f:
            pickle.dump(state, f)
        out = path
    _metrics.counter("checkpoint_saves_total", "trainer checkpoints "
                     "written", labels=("format",)).labels(
        format="manifest" if manifest else "pickle").inc()
    _metrics.gauge("checkpoint_save_ms", "last checkpoint save wall "
                   "time").set((_time.perf_counter() - t0) * 1e3)
    from ..observability import flightrec as _flightrec
    _flightrec.note_event(
        "checkpoint_save", path=str(out),
        ms=round((_time.perf_counter() - t0) * 1e3, 2))
    return out


def load_trainer(trainer, path: str,
                 elastic: Optional[bool] = None) -> dict:
    """Restore a save_trainer checkpoint (either format) into a (re)built
    trainer, resharding onto the trainer's mesh when the checkpoint was
    written on a different one (see restore_trainer's `elastic`).
    Returns the 'extra' metadata dict."""
    import time as _time
    from ..observability import metrics as _metrics
    t0 = _time.perf_counter()
    state = read_checkpoint(path)
    if not isinstance(state, dict) or state.get("format") != _FORMAT:
        raise ValueError(f"{path} is not a {_FORMAT} checkpoint")
    out = restore_trainer(trainer, state, elastic=elastic)
    _metrics.counter("checkpoint_restores_total",
                     "trainer checkpoints restored").inc()
    _metrics.gauge("checkpoint_restore_ms", "last checkpoint restore "
                   "wall time").set((_time.perf_counter() - t0) * 1e3)
    return out


def latest_checkpoint(directory: str, prefix: str = "ckpt-",
                      validate: bool = True, gc_tmp: bool = True):
    """Newest VALID `{prefix}{step}` entry in directory (auto-resume
    lookup, reference AutoCheckpointChecker.get_range_checkpoint_path).

    Candidates failing validate_checkpoint (truncated file, corrupt or
    incomplete manifest dir) are skipped so resume lands on the newest
    checkpoint that will actually load. Stale `.tmp` staging orphans
    from crashed saves are garbage-collected (call sites are resume-time
    — no writer is active; pass gc_tmp=False to scan read-only)."""
    if gc_tmp:
        gc_stale_tmps(directory, prefix)
    for _, full in checkpoint_candidates(directory, prefix):
        if not validate or validate_checkpoint(full):
            return full
    return None
