"""Trainer checkpoint / auto-resume.

Reference: fluid/incubate/checkpoint/auto_checkpoint.py:71
(AutoCheckpointChecker / train_epoch_range: periodic save of
persistables + optimizer accumulators + epoch no, auto-restore on
restart) and fleet.save_persistables; optimizer state in the reference
lives in scope vars named `param@accumulator`, so checkpoint = save
persistable vars.

TPU-native: the compiled trainers own sharded device arrays; checkpoint
= host-gather the pytrees (numpy) + a small metadata dict, restore =
device_put each leaf back with its recorded NamedSharding. The file is
a single pickle (the framework's save format, framework/io.py) — the
shardings themselves are NOT stored, they come from the rebuilt
trainer, so a checkpoint written on one mesh layout restores onto
another (e.g. dp8 -> dp4) as long as the model matches.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional

import numpy as np

import jax

__all__ = ["save_trainer", "load_trainer", "latest_checkpoint"]

_FORMAT = "paddle_tpu_trainer_ckpt_v1"


def _to_host(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def save_trainer(trainer, path: str, extra: Optional[dict] = None) -> str:
    """Persist a trainer's full training state (params + optimizer state
    + step count + LR-scheduler state [+ gradient-merge buffer])."""
    from ..optimizer.lr import LRScheduler
    state = {
        "format": _FORMAT,
        "step_count": trainer._step_count,
        "params": _to_host(trainer.params),
        "opt_state": _to_host(trainer.opt_state),
        "extra": extra or {},
    }
    if getattr(trainer, "buffers", None):
        state["buffers"] = _to_host(trainer.buffers)
    if getattr(trainer, "_grad_buf", None) is not None:
        state["grad_buf"] = _to_host(trainer._grad_buf)
    if getattr(trainer, "_scaler_state", None) is not None:
        state["scaler"] = _to_host(trainer._scaler_state)
    lr = getattr(trainer.optimizer, "_lr", None)
    if isinstance(lr, LRScheduler):
        state["lr_scheduler"] = lr.state_dict()
    # fs backend (reference framework/io/fs.cc): local paths write
    # tmp+rename (atomic — a killed save never corrupts), hdfs:// paths
    # stage locally and upload
    from ..framework.fs import open_for_write
    with open_for_write(path, "wb") as f:
        pickle.dump(state, f)
    return path


def _restore_tree(host_tree, live_tree, shardings):
    """device_put each host leaf with the trainer's sharding, verifying
    structure + shapes against the live state."""
    h_leaves, h_def = jax.tree_util.tree_flatten(host_tree)
    l_leaves, l_def = jax.tree_util.tree_flatten(live_tree)
    if h_def != l_def:
        raise ValueError(
            f"checkpoint structure mismatch: {h_def} vs trainer {l_def}")
    s_leaves = jax.tree_util.tree_flatten(shardings)[0]
    out = []
    for h, l, s in zip(h_leaves, l_leaves, s_leaves):
        if tuple(h.shape) != tuple(l.shape):
            raise ValueError(
                f"checkpoint leaf shape {h.shape} != trainer {l.shape}")
        out.append(jax.device_put(h.astype(l.dtype), s))
    return jax.tree_util.tree_unflatten(l_def, out)


def load_trainer(trainer, path: str) -> dict:
    """Restore `save_trainer` state into a (re)built trainer; shardings
    come from the trainer, so the mesh layout may differ from the one
    that wrote the checkpoint. Returns the 'extra' metadata dict."""
    from ..optimizer.lr import LRScheduler
    from ..framework.fs import open_for_read
    with open_for_read(path, "rb") as f:
        state = pickle.load(f)
    if state.get("format") != _FORMAT:
        raise ValueError(f"{path} is not a {_FORMAT} checkpoint")
    trainer.params = _restore_tree(state["params"], trainer.params,
                                   trainer._param_shardings)
    trainer.opt_state = _restore_tree(state["opt_state"],
                                      trainer.opt_state,
                                      trainer._opt_shardings)
    if "buffers" in state and getattr(trainer, "buffers", None):
        trainer.buffers = _restore_tree(state["buffers"], trainer.buffers,
                                        trainer._buffer_shardings)
    if "grad_buf" in state and getattr(trainer, "_grad_buf", None) \
            is not None:
        trainer._grad_buf = _restore_tree(
            state["grad_buf"], trainer._grad_buf, trainer._grad_shardings)
    if "scaler" in state and getattr(trainer, "_scaler_state", None) \
            is not None:
        trainer._scaler_state = _restore_tree(
            state["scaler"], trainer._scaler_state,
            trainer._scaler_shardings)
    trainer._step_count = int(state["step_count"])
    ksteps = getattr(trainer, "k_steps", 1)
    trainer.optimizer._step_count = trainer._step_count // max(ksteps, 1)
    lr = getattr(trainer.optimizer, "_lr", None)
    if isinstance(lr, LRScheduler) and "lr_scheduler" in state:
        lr.set_state_dict(state["lr_scheduler"])
    return state.get("extra", {})


def latest_checkpoint(directory: str, prefix: str = "ckpt-"):
    """Newest `{prefix}{step}` file in directory (auto-resume lookup,
    reference AutoCheckpointChecker.get_range_checkpoint_path)."""
    if not os.path.isdir(directory):
        return None
    best, best_step = None, -1
    for name in os.listdir(directory):
        if name.startswith(prefix) and not name.endswith(".tmp"):
            try:
                step = int(name[len(prefix):])
            except ValueError:
                continue
            if step > best_step:
                best, best_step = os.path.join(directory, name), step
    return best
