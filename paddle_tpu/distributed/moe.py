"""Mixture-of-Experts / expert parallelism — a from-scratch TPU design.

The reference snapshot has NO MoE and NO all-to-all collective (SURVEY.md
§2.5 marks expert parallelism "ABSENT — design fresh: ICI all-to-all"),
so unlike the rest of the framework there is no reference file to match;
BASELINE.json config #5 (ERNIE-MoE / switch-transformer) is the target
workload.

Design (GShard/Switch-transformer dispatch, expressed two ways):

1. COMPILED GSPMD path (the one SpmdTrainer uses): expert weights are
   stacked [E, ...] and sharded over the 'ep' mesh axis; tokens are
   grouped by batch row and dispatched into an [B, E, C, H] buffer with
   one-hot einsums. Resharding that buffer from token-sharded ('dp' on B)
   to expert-sharded ('ep' on E) is exactly the all-to-all over ICI —
   GSPMD inserts it from the sharding constraint, the same way it inserts
   the grad all-reduce over 'dp'.

2. MANUAL shard_map path: inside shard_map with the 'ep' axis bound the
   dispatch/exchange/combine is written with explicit
   ``lax.all_to_all`` (dispatch E->devices, expert FFN on local experts,
   all_to_all back). Both paths compute the same math; the manual one is
   the single-axis (dp==ep) formulation.

Gating: top-k router with capacity factor; tokens beyond an expert's
capacity C = ceil(cf * k * S / E) are dropped (their combine weight is
zero and the residual connection carries them — Switch semantics). The
load-balance auxiliary loss is E * sum_e(frac_tokens_e * mean_prob_e)
(Switch eq. 4), optionally plus a router z-loss; they reach the training
loss through the collect_aux_losses() collector, which the compiled
trainers open around the model call.
"""
from __future__ import annotations

import contextlib
import math
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..core.autograd import apply
from ..core.tensor import Tensor
from ..nn import initializer as I
from ..nn.layer_base import Layer, ParamAttr
from .mesh import PartitionSpec, get_mesh, NamedSharding
from .mesh import axis_size as _axis_size
from .parallel_layers import mark_sharding, _in_shard_map

__all__ = ["MoELayer", "ExpertParallelFFN", "top_k_gating",
           "collect_aux_losses", "add_aux_loss", "moe_capacity",
           "collect_expert_stats", "record_expert_stats",
           "fold_expert_stats", "nearest_chunk_divisors"]


# ---------------------------------------------------------------------------
# Auxiliary-loss collection: MoE routers produce losses deep inside the
# network that must reach the optimizer's loss. The compiled trainers open
# a collector around the forward; eager users do the same explicitly.
# ---------------------------------------------------------------------------
_AUX_STACK: List[list] = []


@contextlib.contextmanager
def collect_aux_losses():
    """Collect auxiliary losses (router load-balance/z-loss) produced by
    layers during a forward pass. Yields a list the caller sums into the
    training loss."""
    bucket: list = []
    _AUX_STACK.append(bucket)
    try:
        yield bucket
    finally:
        _AUX_STACK.pop()


def add_aux_loss(loss):
    """Layers call this with a scalar Tensor; it lands in the innermost
    open collector (no-op when none is open, e.g. pure inference)."""
    if _AUX_STACK:
        _AUX_STACK[-1].append(loss)


def moe_capacity(tokens_per_group: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Expert capacity per token group (Switch: cf * k * S / E)."""
    return max(1, int(math.ceil(
        capacity_factor * top_k * tokens_per_group / num_experts)))


# ---------------------------------------------------------------------------
# Expert-balance stats: serving wants per-expert load and dropped-token
# (capacity-overflow) accounting without extra host syncs. The engine
# opens a collector inside its jitted step while TRACING; every MoE
# layer the trace hits records its traced kept-token load, and the fold
# rides out of the executable as one extra output fetched at the step's
# existing readback point.
# ---------------------------------------------------------------------------
_EXPERT_STATS_STACK: List[list] = []


@contextlib.contextmanager
def collect_expert_stats():
    """Collect per-layer expert-balance stats (kept-token load [E] +
    statically-known assigned count) emitted by MoE layers during a
    forward trace. Yields the list; fold with fold_expert_stats()."""
    bucket: list = []
    _EXPERT_STATS_STACK.append(bucket)
    try:
        yield bucket
    finally:
        _EXPERT_STATS_STACK.pop()


def record_expert_stats(load, assigned: int):
    """MoE layers call this with their per-expert KEPT-token counts
    ``load [E]`` (dispatch mask sums — may be traced) and the static
    number of (token, expert) assignments the router made
    (``top_k * B * S``); dropped = assigned - sum(load). No-op when no
    collector is open (training, eager use)."""
    if _EXPERT_STATS_STACK:
        _EXPERT_STATS_STACK[-1].append(
            {"load": load, "assigned": int(assigned)})


def fold_expert_stats(bucket):
    """Sum a collector's per-layer records into ONE fixed-shape pytree
    ``{"load": [E] f32, "assigned": f32 scalar}`` suitable as an extra
    jit output; None when the trace hit no MoE layer (static per model
    config, so executable signatures stay stable)."""
    if not bucket:
        return None
    load = bucket[0]["load"].astype(jnp.float32)
    for rec in bucket[1:]:
        load = load + rec["load"].astype(jnp.float32)
    assigned = jnp.asarray(
        float(sum(r["assigned"] for r in bucket)), jnp.float32)
    return {"load": load, "assigned": assigned}


def nearest_chunk_divisors(n: int, k: int):
    """The valid a2a chunk counts nearest a requested k: the largest
    divisor of n that is <= k and the smallest that is >= k (for the
    divisibility error message — naming what WOULD work beats
    restating the constraint)."""
    k = max(1, min(int(k), int(n)))
    lower = next(d for d in range(k, 0, -1) if n % d == 0)
    higher = next(d for d in range(k, n + 1) if n % d == 0)
    return lower, higher


# ---------------------------------------------------------------------------
# Router math (pure jnp — used under both dispatch paths)
# ---------------------------------------------------------------------------
def top_k_gating(logits, top_k: int, capacity: int,
                 normalize_gates: bool = True):
    """Top-k gating with per-group capacity.

    logits: [B, S, E] router scores (a group = one batch row).
    Returns (dispatch [B,S,E,C] 0/1, combine [B,S,E,C] float, aux, zloss):
      - dispatch[b,s,e,c]=1 iff token s goes to expert e at capacity
        slot c;
      - combine = dispatch * renormalized gate prob;
      - aux = E * sum_e(load_frac_e * mean_prob_e) (Switch load-balance);
      - zloss = mean(logsumexp(logits)^2) (router logit drift control).
    """
    f32 = logits.astype(jnp.float32)
    probs = jax.nn.softmax(f32, axis=-1)                       # [B,S,E]
    n_experts = probs.shape[-1]

    masks, gates = [], []
    remaining = probs
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                   # [B,S]
        m = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)  # [B,S,E]
        masks.append(m)
        gates.append(jnp.sum(probs * m, axis=-1))              # [B,S]
        remaining = remaining * (1.0 - m)

    # load-balance aux from the top-1 assignment (Switch eq. 4)
    load_frac = jnp.mean(masks[0], axis=1)                     # [B,E]
    mean_prob = jnp.mean(probs, axis=1)                        # [B,E]
    aux = n_experts * jnp.mean(jnp.sum(load_frac * mean_prob, axis=-1))
    zloss = jnp.mean(jnp.square(jax.nn.logsumexp(f32, axis=-1)))

    if normalize_gates and top_k > 1:
        denom = sum(gates) + 1e-9
        gates = [g / denom for g in gates]

    dispatch = jnp.zeros(probs.shape + (capacity,), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    # running per-expert fill count across the k choices
    offset = jnp.zeros(probs.shape[:1] + (1, n_experts), jnp.float32)
    for m, g in zip(masks, gates):
        pos_e = jnp.cumsum(m, axis=1) - m + offset             # [B,S,E]
        offset = offset + jnp.sum(m, axis=1, keepdims=True)
        pos = jnp.sum(pos_e * m, axis=-1)                      # [B,S]
        keep = (pos < capacity) & (jnp.sum(m, axis=-1) > 0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32) * keep[..., None]
        d = m[..., :, None] * slot[..., None, :]       # [B,S,E,C]
        dispatch = dispatch + d
        combine = combine + d * g[..., None, None]
    return dispatch, combine, aux, zloss


# ---------------------------------------------------------------------------
# Layers
# ---------------------------------------------------------------------------
class ExpertParallelFFN(Layer):
    """E stacked FFN experts, weights sharded over the 'ep' mesh axis.

    Parameters are the batched analogue of GPTMLP: w_up [E, H, F],
    w_down [E, F, H]; each expert e computes
    down(act(up(x_e))) on its capacity slice.
    """

    def __init__(self, num_experts: int, hidden_size: int, ffn_size: int,
                 weight_attr=None, down_weight_attr=None,
                 ep_axis: str = "ep", activation: str = "gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.hidden_size = hidden_size
        self.ffn_size = ffn_size
        self.ep_axis = ep_axis
        self.activation = activation
        self.w_up = self.create_parameter(
            [num_experts, hidden_size, ffn_size], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.b_up = self.create_parameter(
            [num_experts, ffn_size], is_bias=True)
        self.w_down = self.create_parameter(
            [num_experts, ffn_size, hidden_size],
            attr=down_weight_attr or weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.b_down = self.create_parameter(
            [num_experts, hidden_size], is_bias=True)
        for p in (self.w_up, self.b_up, self.w_down, self.b_down):
            mark_sharding(p, PartitionSpec(ep_axis,
                                           *([None] * (p.ndim - 1))))

    def act(self, x):
        if self.activation == "gelu":
            return jax.nn.gelu(x, approximate=True)
        if self.activation == "relu":
            return jax.nn.relu(x)
        raise ValueError(f"unknown activation {self.activation}")


class MoELayer(Layer):
    """Switch/GShard MoE layer: router + expert-parallel FFN + combine.

    Drop-in replacement for an MLP block: forward(x [B,S,H]) -> [B,S,H].
    Router aux losses are emitted via add_aux_loss() (scaled by
    aux_loss_coeff / z_loss_coeff) AND kept on self.last_aux_loss for
    direct inspection.
    """

    def __init__(self, hidden_size: int, ffn_size: int, num_experts: int,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 aux_loss_coeff: float = 0.01, z_loss_coeff: float = 0.0,
                 normalize_gates: bool = True, ep_axis: str = "ep",
                 weight_attr=None, down_weight_attr=None,
                 activation: str = "gelu",
                 a2a_chunks: Optional[int] = None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_coeff = aux_loss_coeff
        self.z_loss_coeff = z_loss_coeff
        self.normalize_gates = normalize_gates
        self.ep_axis = ep_axis
        # chunked all-to-all (shard_map path): K > 1 splits dispatch/
        # combine so chunk j's exchange overlaps chunk j-1's expert FFN;
        # None resolves per-trace from PADDLE_TPU_MOE_A2A_CHUNKS /
        # PADDLE_TPU_OVERLAP (distributed.overlap.moe_a2a_chunks)
        self.a2a_chunks = a2a_chunks
        self.gate = self.create_parameter(
            [hidden_size, num_experts],
            attr=weight_attr, default_initializer=I.Normal(0.0, 0.02))
        # router stays replicated: every device scores its own tokens
        mark_sharding(self.gate, PartitionSpec(None, None))
        self.experts = ExpertParallelFFN(
            num_experts, hidden_size, ffn_size, weight_attr=weight_attr,
            down_weight_attr=down_weight_attr, ep_axis=ep_axis,
            activation=activation)
        self.last_aux_loss: Optional[Tensor] = None

    # -- dense/GSPMD formulation -------------------------------------
    def _fn_dense(self, x, gate, w_up, b_up, w_down, b_down):
        s = x.shape[1]
        cap = moe_capacity(s, self.num_experts, self.top_k,
                           self.capacity_factor)
        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32), gate)
        dispatch, combine, aux, zloss = top_k_gating(
            logits, self.top_k, cap, self.normalize_gates)
        load = jnp.sum(dispatch, axis=(0, 1, 3))     # [E] kept tokens
        dispatch = dispatch.astype(x.dtype)
        combine = combine.astype(x.dtype)
        # token->expert buffer; resharding B('dp') -> E('ep') here IS the
        # all-to-all, inserted by GSPMD from the sharding constraint
        xe = jnp.einsum("bsec,bsh->bech", dispatch, x)   # [B,E,C,H]
        xe = self._constrain(xe, PartitionSpec("dp", self.ep_axis,
                                               None, None))
        h1 = self.experts.act(
            jnp.einsum("bech,ehf->becf", xe, w_up.astype(x.dtype))
            + b_up.astype(x.dtype)[None, :, None, :])
        ye = jnp.einsum("becf,efh->bech", h1, w_down.astype(x.dtype)) \
            + b_down.astype(x.dtype)[None, :, None, :]
        ye = self._constrain(ye, PartitionSpec("dp", self.ep_axis,
                                               None, None))
        y = jnp.einsum("bsec,bech->bsh", combine, ye)
        return y, aux, zloss, load

    # -- explicit all_to_all formulation (inside shard_map, dp==ep) --
    def _fn_shard_map(self, x, gate, w_up, b_up, w_down, b_down):
        axis = self.ep_axis
        world = _axis_size(axis)
        b, s, h = x.shape                       # local batch shard
        e_loc = w_up.shape[0]                   # local experts
        n_exp = e_loc * world
        cap = moe_capacity(s, n_exp, self.top_k, self.capacity_factor)
        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32), gate)
        dispatch, combine, aux, zloss = top_k_gating(
            logits, self.top_k, cap, self.normalize_gates)
        aux = jax.lax.pmean(aux, axis)
        zloss = jax.lax.pmean(zloss, axis)
        dispatch = dispatch.astype(x.dtype)
        combine = combine.astype(x.dtype)
        xe = jnp.einsum("bsec,bsh->ebch", dispatch, x)   # [E,b,C,H]
        xe = xe.reshape(n_exp, b * cap, h)

        def expert_ffn(xg):
            """Local experts over a token-slot slice [E_loc, g, H] —
            pointwise per token, so chunking the slot dim is exact."""
            h1 = self.experts.act(
                jnp.einsum("egh,ehf->egf", xg, w_up.astype(x.dtype))
                + b_up.astype(x.dtype)[:, None, :])
            return jnp.einsum("egf,efh->egh", h1,
                              w_down.astype(x.dtype)) \
                + b_down.astype(x.dtype)[:, None, :]

        # chunked dispatch/combine (GShard-style a2a splitting): chunk
        # j+1's exchange has no dependence on chunk j's FFN, so the
        # async-collective scheduler can run them concurrently; K=1 is
        # the monolithic synchronous exchange.  Identical math either
        # way — the chunks partition the token-slot dim.
        if self.a2a_chunks is not None:
            # an explicit K that doesn't divide would be silently
            # rewritten — someone A/B-measuring chunk counts must not
            # get numbers for a different K than they asked for
            k = int(self.a2a_chunks)
            if k < 1 or (b * cap) % k:
                lo, hi = nearest_chunk_divisors(b * cap, k)
                raise ValueError(
                    f"a2a_chunks={k} must divide the per-device token "
                    f"slots b*capacity={b * cap} (b={b}, capacity="
                    f"{cap}); the nearest valid chunk counts are "
                    f"{lo} (below) and {hi} (above) — pick one, or "
                    f"leave a2a_chunks=None for the auto-clamped "
                    f"default")
        else:
            # env/default resolution clamps to the nearest divisor
            from .overlap import moe_a2a_chunks as _resolve_chunks
            k = _resolve_chunks(b * cap)
        csz = (b * cap) // k
        ye_chunks = []
        for j in range(k):
            xj = jax.lax.slice_in_dim(xe, j * csz, (j + 1) * csz, axis=1)
            # dispatch: each device keeps its expert rows of everyone's
            # tokens in this chunk
            xj = jax.lax.all_to_all(xj, axis, split_axis=0,
                                    concat_axis=1,
                                    tiled=True)      # [E_loc, W*csz, H]
            yj = expert_ffn(xj)
            # combine: return this chunk's expert outputs to the owners
            yj = jax.lax.all_to_all(yj, axis, split_axis=1,
                                    concat_axis=0,
                                    tiled=True)      # [E, csz, H]
            ye_chunks.append(yj)
        ye = ye_chunks[0] if k == 1 else jnp.concatenate(ye_chunks,
                                                         axis=1)
        ye = ye.reshape(n_exp, b, cap, h)
        y = jnp.einsum("bsec,ebch->bsh", combine, ye)
        return y, aux, zloss

    # -- serving formulation: ep-sharded experts, replicated tokens ---
    def _serve_ep_mesh(self):
        """The compile mesh when the expert-parallel SERVING dispatch
        can run for this trace, else None.  Conditions: inference (the
        training formulations own their paths), a compile mesh bound by
        the engine's trace guard carrying a real 'ep' axis, divisible
        experts, and not already inside a shard_map."""
        if self.training or _in_shard_map(self.ep_axis):
            return None
        from .mesh import get_compile_mesh
        mesh = get_compile_mesh()
        if (mesh is None or self.ep_axis not in mesh.axis_names
                or mesh.shape[self.ep_axis] <= 1):
            return None
        if self.num_experts % mesh.shape[self.ep_axis]:
            return None
        return mesh

    def _serve_chunks(self, c_loc: int) -> int:
        """a2a chunk count for the serving dispatch: an explicit
        a2a_chunks must divide the per-device capacity slice c_loc (the
        chunks partition it); None resolves from the overlap knob
        (PADDLE_TPU_MOE_A2A_CHUNKS / tuning-table op 'moe_a2a_chunks')
        and clamps DOWN to the nearest divisor."""
        if self.a2a_chunks is not None:
            k = int(self.a2a_chunks)
            if k < 1 or c_loc % k:
                lo, hi = nearest_chunk_divisors(c_loc, k)
                raise ValueError(
                    f"a2a_chunks={k} must divide the per-device "
                    f"capacity slice {c_loc} of the serving expert "
                    f"dispatch; the nearest valid chunk counts are "
                    f"{lo} (below) and {hi} (above) — pick one, or "
                    f"leave a2a_chunks=None for the auto-clamped "
                    f"default")
            return k
        from .overlap import moe_a2a_chunks as _resolve_chunks
        k = max(1, min(_resolve_chunks(c_loc), c_loc))
        while c_loc % k:
            k -= 1
        return k

    def _fn_serve_ep(self, mesh, x, gate, w_up, b_up, w_down, b_down):
        """Expert-parallel SERVING dispatch (decode [B,1,H], verify
        [B,W,H], prefill [1,S,H]) under shard_map over the full serving
        mesh: tokens and the router stay replicated — every device
        computes the FULL gating, bitwise the ep=1 dense formulation,
        which is what keeps ep>1 token-identical — while expert weights
        arrive ep-sharded.  Each device owns a 1/ep slice of the
        capacity dim: chunked all-to-all sends its slice's tokens to
        the experts' owners (split E, concat C), the local expert FFN
        runs, the reverse all-to-all returns outputs, and a partial
        combine + psum over 'ep' rebuilds the replicated [B,S,H].  The
        capacity dim is zero-padded up front so the slices are equal —
        padded slots carry zero combine weight, so shapes are fixed
        (the zero-recompile contract survives) and the math is exact.
        """
        from .mesh import shard_map
        axis = self.ep_axis
        ep = int(mesh.shape[axis])
        b, s, h = x.shape
        n_exp = self.num_experts
        cap = moe_capacity(s, n_exp, self.top_k, self.capacity_factor)
        cap_pad = -(-cap // ep) * ep
        c_loc = cap_pad // ep
        n_chunks = self._serve_chunks(c_loc)
        csz = c_loc // n_chunks

        def body(xs, gate_r, wu, bu, wd, bd):
            logits = jnp.einsum("bsh,he->bse",
                                xs.astype(jnp.float32), gate_r)
            dispatch, combine, aux, zloss = top_k_gating(
                logits, self.top_k, cap, self.normalize_gates)
            load = jnp.sum(dispatch, axis=(0, 1, 3))   # [E] kept
            dispatch = dispatch.astype(xs.dtype)
            combine = combine.astype(xs.dtype)
            xe = jnp.einsum("bsec,bsh->ebch", dispatch, xs)  # [E,b,C,H]
            if cap_pad > cap:
                xe = jnp.pad(xe, ((0, 0), (0, 0),
                                  (0, cap_pad - cap), (0, 0)))
                combine = jnp.pad(combine, ((0, 0), (0, 0), (0, 0),
                                            (0, cap_pad - cap)))
            idx = jax.lax.axis_index(axis)
            x_loc = jax.lax.dynamic_slice_in_dim(
                xe, idx * c_loc, c_loc, axis=2)        # [E,b,c_loc,H]

            def expert_ffn(xg):
                """Local experts over a capacity-slice chunk
                [E_loc, b, g, H] — pointwise per token slot, so
                chunking the slice is exact."""
                h1 = self.experts.act(
                    jnp.einsum("ebgh,ehf->ebgf", xg,
                               wu.astype(xs.dtype))
                    + bu.astype(xs.dtype)[:, None, None, :])
                return jnp.einsum("ebgf,efh->ebgh", h1,
                                  wd.astype(xs.dtype)) \
                    + bd.astype(xs.dtype)[:, None, None, :]

            ye_chunks = []
            for j in range(n_chunks):
                xj = jax.lax.slice_in_dim(
                    x_loc, j * csz, (j + 1) * csz, axis=2)
                # dispatch: each device keeps its expert rows of every
                # peer's capacity slice for this chunk
                xj = jax.lax.all_to_all(
                    xj, axis, split_axis=0, concat_axis=2,
                    tiled=True)                  # [E_loc, b, csz*ep, H]
                yj = expert_ffn(xj)
                # combine: return the chunk's outputs to slice owners
                yj = jax.lax.all_to_all(
                    yj, axis, split_axis=2, concat_axis=0,
                    tiled=True)                  # [E, b, csz, H]
                ye_chunks.append(yj)
            ye = ye_chunks[0] if n_chunks == 1 else \
                jnp.concatenate(ye_chunks, axis=2)   # [E, b, c_loc, H]
            comb_loc = jax.lax.dynamic_slice_in_dim(
                combine, idx * c_loc, c_loc, axis=3)
            y = jnp.einsum("bsec,ebch->bsh", comb_loc, ye)
            y = jax.lax.psum(y, axis)
            return y, aux, zloss, load

        P = PartitionSpec
        sm = shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(), P(), P(), P()),
            # gating/combine are replicated by construction (identical
            # inputs on every device) but flow through axis_index-
            # derived slices the static replication checker cannot see
            # through; the psum re-establishes the invariant
            check_vma=False)
        return sm(x, gate, w_up, b_up, w_down, b_down)

    def _constrain(self, arr, spec: PartitionSpec):
        """Best-effort sharding constraint: applied only under the
        COMPILE mesh a trainer publishes while tracing its step
        (mesh.compile_mesh_guard) — the ambient default mesh must not
        leak constraints into eager tape traces. Identity otherwise:
        GSPMD propagation from the sharded expert weights still finds
        the layout. Axes that don't divide the dim (ragged batches)
        drop to replicated, and shard_map manual mode is skipped."""
        from .mesh import get_compile_mesh
        mesh = get_compile_mesh()
        if mesh is None or not isinstance(arr, jax.core.Tracer):
            return arr
        if any(_in_shard_map(a) for a in mesh.axis_names):
            return arr
        names = [a if (a in mesh.axis_names and mesh.shape[a] > 1 and
                       arr.shape[i] % mesh.shape[a] == 0)
                 else None for i, a in enumerate(spec)]
        if not any(names):
            return arr
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, PartitionSpec(*names)))

    def forward(self, x):
        import functools
        in_sm = _in_shard_map(self.ep_axis)
        serve_mesh = None if in_sm else self._serve_ep_mesh()
        if in_sm:
            fn = self._fn_shard_map
        elif serve_mesh is not None:
            # serving trace (engine compile-mesh guard) with a real
            # 'ep' axis: ep-sharded experts + explicit chunked a2a
            fn = functools.partial(self._fn_serve_ep, serve_mesh)
        else:
            if self.a2a_chunks not in (None, 1):
                # the GSPMD path's all-to-all is XLA-inserted (no
                # manual exchange to chunk); silently ignoring an
                # explicit K here would hand an A/B measurement the
                # monolithic numbers
                raise NotImplementedError(
                    f"a2a_chunks={self.a2a_chunks} only applies to the "
                    f"shard_map expert-parallel formulations (the '"
                    f"{self.ep_axis}' axis bound inside shard_map, or "
                    f"the serving dispatch on an ep>1 mesh); the GSPMD "
                    f"path's all-to-all is inserted by XLA and cannot "
                    f"be chunked from here — leave a2a_chunks=None")
            fn = self._fn_dense
        out = apply(
            fn, x, self.gate, self.experts.w_up, self.experts.b_up,
            self.experts.w_down, self.experts.b_down, name="moe_layer")
        if len(out) == 4:
            y, aux, zloss, load = out
            arr = x.data if isinstance(x, Tensor) else jnp.asarray(x)
            record_expert_stats(
                load.data if isinstance(load, Tensor) else load,
                self.top_k * arr.shape[0] * arr.shape[1])
        else:
            y, aux, zloss = out
        total_aux = aux * self.aux_loss_coeff
        if self.z_loss_coeff:
            total_aux = total_aux + zloss * self.z_loss_coeff
        # keep for inspection only when concrete — storing a trace-time
        # tracer would raise UnexpectedTracerError on later reads
        arr = total_aux.data if isinstance(total_aux, Tensor) else total_aux
        self.last_aux_loss = None if isinstance(arr, jax.core.Tracer) \
            else total_aux
        add_aux_loss(total_aux)
        return y
