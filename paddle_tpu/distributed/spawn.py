"""paddle.distributed.spawn — multiprocessing entry for dygraph.

Reference: python/paddle/distributed/spawn.py:276 (spawn: start nprocs
python processes running func(rank, *args) with the PADDLE_* env set,
join and re-raise child failures). TPU-native: children rendezvous via
the JAX coordinator address exported in the env (env.init_parallel_env),
and children can be pinned to a specific jax platform via
spawn(..., backend='cpu') so single-host CPU rings (the reference's
localhost test strategy) work on machines with one real accelerator.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import time
import traceback
from typing import Optional, Tuple

from .launch import find_free_port, trainer_env_vars

__all__ = ["spawn", "SpawnContext"]


def _worker(func, rank, world, coordinator, endpoints, args, err_q,
            backend):
    try:
        os.environ.update(
            trainer_env_vars(rank, world, endpoints, coordinator))
        if backend:
            # pin the child's jax platform BEFORE it imports jax; for
            # cpu also scrub TPU-plugin env hooks (a sitecustomize keyed
            # on PALLAS_AXON_* would otherwise bind every child to the
            # one real TPU chip)
            os.environ["JAX_PLATFORMS"] = backend
            if backend == "cpu":
                for k in list(os.environ):
                    if k.startswith(("AXON_", "PALLAS_AXON_", "TPU_")):
                        del os.environ[k]
        func(rank, *args)
    except Exception:
        err_q.put((rank, traceback.format_exc()))
        raise


class SpawnContext:
    def __init__(self, procs, err_q):
        self.processes = procs
        self._err_q = err_q

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for all workers; on the FIRST failure terminate the
        survivors (they may be blocked in a collective waiting for the
        dead rank) and re-raise — the reference spawn's watch loop."""
        deadline = time.time() + timeout if timeout is not None else None

        def fail(rank=None, tb=None, codes=None):
            for p in self.processes:
                if p.is_alive():
                    p.terminate()
            for p in self.processes:
                p.join(5)
            if tb is not None:
                raise RuntimeError(
                    f"spawned trainer rank {rank} failed:\n{tb}")
            raise RuntimeError(f"spawned trainers exited with {codes}")

        while True:
            if not self._err_q.empty():
                rank, tb = self._err_q.get()
                fail(rank=rank, tb=tb)
            bad = [p.exitcode for p in self.processes
                   if p.exitcode not in (0, None)]
            if bad:
                # give the failed rank a moment to flush its traceback
                time.sleep(0.2)
                if not self._err_q.empty():
                    rank, tb = self._err_q.get()
                    fail(rank=rank, tb=tb)
                fail(codes=bad)
            if all(not p.is_alive() for p in self.processes):
                return True
            if deadline and time.time() > deadline:
                return False
            time.sleep(0.1)


def spawn(func, args: Tuple = (), nprocs: int = 2, join: bool = True,
          daemon: bool = False, backend: Optional[str] = None,
          **options):
    """Start `nprocs` processes running func(rank, *args) (reference
    spawn.py:276). Returns a SpawnContext (join=False) or joins.

    backend: jax platform to pin the children to (None = inherit the
    parent's platform selection, matching the reference's behavior).
    Pass backend='cpu' for single-host CPU rings on a machine with one
    real accelerator — otherwise every child grabs the same chip."""
    ctx = mp.get_context("spawn")
    err_q = ctx.Queue()
    coordinator = f"127.0.0.1:{find_free_port()}"
    endpoints = [f"127.0.0.1:{find_free_port()}" for _ in range(nprocs)]
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(
            target=_worker,
            args=(func, rank, nprocs, coordinator, endpoints, args, err_q,
                  backend),
            daemon=daemon)
        p.start()
        procs.append(p)
    sctx = SpawnContext(procs, err_q)
    if join:
        sctx.join()
        return None
    return sctx
