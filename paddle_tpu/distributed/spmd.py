"""Compiled SPMD trainer — the ParallelExecutor replacement.

Reference mapping:
- ParallelExecutor (/root/reference/paddle/fluid/framework/
  parallel_executor.cc:609) built an SSA graph per device, inserted
  AllReduceOpHandles (ir/multi_devices_graph_pass/
  multi_devices_graph_pass.cc:484,1200) and drained it with a threaded
  scheduler. Here ONE jit'd function (forward + backward + optimizer
  update) is compiled by XLA under a `jax.sharding.Mesh`; GSPMD inserts
  and fuses the collectives (grad all-reduce over 'dp', tensor-parallel
  all-gather/reduce-scatter over 'tp') that the reference hand-scheduled.
- Fleet meta-optimizer program rewrites (sharding_optimizer.py:69-120,
  amp_optimizer.py, gradient_merge_optimizer.py, recompute_optimizer.py)
  become constructor-time choices of sharding specs / dtypes / extra
  buffers on the SAME compiled step — no program surgery.

ZeRO (strategy.sharding, reference sharding_optimizer.py):
  stage 1: optimizer state sharded over 'dp'
  stage 2: + the gradient-merge accumulation buffer sharded over 'dp'
  stage 3: + parameters sharded over 'dp' (XLA all-gathers per-layer at
           use, the GSPMD analogue of the reference's broadcast-on-demand
           program segments)

Every enabled-but-unimplemented strategy flag raises — flags either work
or fail loudly (round-1 verdict: silent flags are worse than errors).
"""
from __future__ import annotations

import itertools
import os
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..func import functional_call
from ..nn.layer_base import Layer
from ..observability import capture as _capture
from ..observability import doctor as _doctor
from ..observability import exec_registry as _exec_registry
from ..observability import flightrec as _flightrec
from ..observability import metrics as _metrics
from ..observability import spans as _spans
from ..observability import watchdog as _watchdog

# telemetry/observatory component ids: one per trainer instance
_TRAINER_IDS = itertools.count()

# executable-observatory kinds per compiled-key family (ISSUE 15)
_EXEC_KINDS = {"fused": "train_step", "fused_out": "train_step",
               "accum": "train_step", "update": "grad_update",
               "eval": "eval"}
from . import async_dispatch
from .async_dispatch import StepResult
from .fleet.strategy import DistributedStrategy
from .mesh import (Mesh, NamedSharding, PartitionSpec, default_mesh,
                   compile_mesh_guard)

__all__ = ["SpmdTrainer", "dp_train_step", "zero_sharding_spec",
           "build_param_specs", "StepResult", "tuned_remat_policy",
           "remat_policy_key"]


def _is_floating(a) -> bool:
    return jnp.issubdtype(a.dtype, jnp.floating)


def remat_policy_key(cfg):
    """Tuning-table key for the measured remat-policy choice: the model
    shape dims that move the save-dots-vs-full trade-off.  None when the
    model carries no recognizable config."""
    h = getattr(cfg, "hidden_size", None)
    if not h:
        return None
    from ..utils import tuning as _tuning
    return (_tuning.device_kind(), int(h),
            int(getattr(cfg, "num_layers", 0) or 0),
            int(getattr(cfg, "max_seq_len", 0) or 0))


def tuned_remat_policy(model):
    """The unified tuning table's measured remat policy (op
    "remat_policy": 'dots_no_batch' / 'dots' / 'full', recorded by
    bench.py's sweep winner) for this device + model shape — exact key
    first, then the nearest tabled shape.  Entries recorded as
    'off'/'none' mean the sweep's winner ran WITHOUT remat; a trainer
    that was asked for remat ignores them (returns None).  None when
    nothing applicable is tabled."""
    cfg = getattr(model, "cfg", None)
    key = remat_policy_key(cfg) if cfg is not None else None
    if key is None:
        return None
    from ..utils import tuning as _tuning
    # bounded nearest (each shape dim within ~2× overall): a policy
    # measured on a 125m model must NOT silently drive remat for a
    # multi-billion-param config — dots-saveable retains activations a
    # bigger model may not have memory for
    val = _tuning.lookup_nearest("remat_policy", key, match_idx=(0,),
                                 near_idx=(1, 2, 3), max_dist=2.1)
    if not isinstance(val, str) or val.lower() in ("off", "none", ""):
        return None
    return val


def zero_sharding_spec(shape, base_spec: PartitionSpec, dp_axis: str,
                       dp_size: int) -> PartitionSpec:
    """Extend `base_spec` (tensor-parallel placement, maybe empty) with a
    'dp' sharding on the largest free dim divisible by dp_size — the GSPMD
    expression of the reference's param->rank assignment
    (sharding_optimizer.py `shard` / `_split_program`). Small params
    (biases, norms) that don't divide stay replicated, like the
    reference's below-threshold segments."""
    if dp_size <= 1 or not shape or dp_axis in tuple(base_spec):
        return base_spec
    spec = list(base_spec) + [None] * (len(shape) - len(base_spec))
    # pick the largest unsharded dim divisible by dp_size
    best, best_dim = -1, None
    for i, (s, d) in enumerate(zip(spec, shape)):
        if s is None and d % dp_size == 0 and d > best:
            best, best_dim = d, i
    if best_dim is None or best < dp_size:
        return base_spec
    spec[best_dim] = dp_axis
    return PartitionSpec(*spec)


def build_param_specs(model: Layer, mesh: Mesh, dp_axis: str = "dp",
                      zero_stage: int = 0) -> Dict[str, PartitionSpec]:
    """name -> PartitionSpec for every parameter: tensor-parallel specs
    marked by parallel layers (param.pspec), plus ZeRO-3 dp sharding."""
    dp_size = mesh.shape.get(dp_axis, 1) if dp_axis in mesh.axis_names else 1
    specs = {}
    for name, p in model.named_parameters():
        base = getattr(p, "pspec", None) or PartitionSpec()
        # drop axes the mesh doesn't have (e.g. 'tp' specs on a dp-only
        # mesh fall back to replicated, matching nranks==1 fast paths)
        base = PartitionSpec(*[
            a if (a is not None and a in mesh.axis_names and
                  mesh.shape[a] > 1) else None
            for a in base])
        if zero_stage >= 3:
            base = zero_sharding_spec(tuple(p.data.shape), base, dp_axis,
                                      dp_size)
        specs[name] = base
    return specs


class SpmdTrainer:
    """One XLA executable per (train/eval) step over a device mesh.

    Parameters
    ----------
    model : Layer — the network; tensor-parallel layers may carry
        param.pspec annotations which are honored on the mesh.
    optimizer : paddle_tpu.optimizer.Optimizer — its functional form
        (init_state/apply_gradients) runs inside the compiled step.
    loss_fn : callable(outputs, labels) -> scalar Tensor/array.
    mesh : jax.sharding.Mesh with a 'dp' (and optionally 'tp', ...) axis.
    strategy : DistributedStrategy — amp / sharding / gradient_merge /
        recompute knobs are honored; enabled-but-unsupported knobs raise.
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Callable,
                 mesh: Optional[Mesh] = None,
                 strategy: Optional[DistributedStrategy] = None,
                 dp_axis: str = "dp", sp_axis: Optional[str] = None,
                 donate: bool = True,
                 anomaly_policy: Optional[str] = None,
                 comm_stats: Optional[bool] = None,
                 resume_elastic: Optional[bool] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or default_mesh()
        self.strategy = strategy or DistributedStrategy()
        self.dp_axis = dp_axis
        # elastic resume (ISSUE 10): checkpoints record their logical
        # mesh; loading one written on a DIFFERENT topology reshards
        # every leaf onto this trainer's mesh.  True/None allow it
        # (None = env default), False makes a cross-topology restore an
        # error — for jobs whose numerics must be bitwise-stable.
        if resume_elastic is None:
            resume_elastic = os.environ.get(
                "PADDLE_TPU_RESUME_ELASTIC", "1") != "0"
        self.resume_elastic = bool(resume_elastic)
        self._reshard_restores = 0
        self._last_restore_info: Optional[dict] = None
        # sequence-parallel axis: explicit arg > model config > "sp"
        self.sp_axis = sp_axis or getattr(
            getattr(model, "config", None), "sp_axis", None) or "sp"
        self._donate = donate
        self._step_count = 0

        # persistent XLA compile cache (PADDLE_TPU_COMPILE_CACHE): warm
        # restarts skip the multi-minute recompile of identical steps
        from ..utils.compile_cache import ensure_compile_cache
        ensure_compile_cache()

        # step-time breakdown (trainer.stats / bench JSON): where did the
        # wall clock go — waiting for data, placing it, dispatching the
        # compiled step, or blocked on a host sync.  compile_ms_cold is
        # the first-call cost per executable in THIS process (trace +
        # compile or persistent-cache deserialize + first run).
        self._timings = {
            "data_wait_ms": 0.0, "h2d_ms": 0.0, "dispatch_ms": 0.0,
            "sync_ms": 0.0, "compile_ms_cold": 0.0, "steps_timed": 0,
        }
        # h2d_ms is written by BOTH the train thread and a
        # DevicePrefetcher thread (shard_batch runs on each); the
        # read-modify-write needs a lock or increments get lost
        import threading
        self._timings_lock = threading.Lock()
        self._first_call_keys: set = set()

        # unified telemetry (observability/): per-step wall timer (the
        # once-orphaned profiler.StepTimer), registry metrics, and the
        # PADDLE_TPU_PROFILE capture window.  Children are bound ONCE
        # here so the per-step cost is attribute arithmetic; when the
        # env is unset the window is a literal None (one check/step).
        from ..profiler import StepTimer
        self.step_timer = StepTimer(warmup=1)
        self.step_timer.start()
        self._profile = _capture.ProfileWindow.from_env(kind="train")
        self._m_steps = _metrics.counter(
            "train_steps_total", "completed train steps",
            labels=("trainer",)).labels(trainer="spmd")
        self._m_step_ms = _metrics.gauge(
            "train_step_time_ms", "last per-step wall time (host)",
            labels=("trainer",)).labels(trainer="spmd")
        self._m_step_hist = _metrics.histogram(
            "train_step_ms", "per-step wall time",
            labels=("trainer",)).labels(trainer="spmd")
        # flight recorder + stall watchdog (observability): crash hooks
        # installed once per process; the watchdog thread is created on
        # the first step only when PADDLE_TPU_WATCHDOG_S arms it
        _flightrec.install()
        self.watchdog: Optional[_watchdog.Watchdog] = None
        self._wd_checked = False
        # live autotune tier (PADDLE_TPU_AUTOTUNE=live) — ADVISORY on a
        # trainer: train knobs retrace, so a sustained step-time
        # regression ships doctor verdicts (flightrec event) instead of
        # mutating config mid-run.  None when unarmed.
        from ..autotune.live import arm_trainer as _arm_autotune
        self._retuner = _arm_autotune(self)

        # collective breakdown (comm_ms/comm_fraction in trainer.stats):
        # opt-in — measuring it AOT-compiles each step executable a
        # second time, which the tight test/CI budgets cannot afford by
        # default (bench/dryrun turn it on)
        self._comm_enabled = bool(
            comm_stats if comm_stats is not None
            else os.environ.get("PADDLE_TPU_COMM_STATS") == "1")
        self._comm: Dict[Any, dict] = {}

        st = self.strategy
        if st.pipeline:
            raise NotImplementedError(
                "strategy.pipeline: use paddle_tpu.distributed.pipeline."
                "GPipeTrainer for pipeline parallelism")
        # flags either work here or raise — audit EVERY enabled boolean,
        # not a hand-picked subset (silent flags are worse than errors)
        supported = {
            "amp", "recompute", "sharding", "gradient_merge",
            "qat",                      # fake-quant matmuls (see below)
            "tensor_parallel",          # honored via param.pspec + mesh
            "find_unused_parameters",   # moot: XLA zero-grads unused params
            "fuse_all_reduce_ops",      # moot: XLA fuses collectives
            "use_hierarchical_allreduce",  # moot: XLA picks the algorithm
        }
        for key, val in st.to_dict().items():
            if val is True and key not in supported:
                raise NotImplementedError(
                    f"DistributedStrategy.{key} is not implemented in the "
                    f"compiled trainer; supported flags: {sorted(supported)}")

        self.zero_stage = int(st.sharding_configs.get("stage", 2)) \
            if st.sharding else 0
        self.k_steps = int(st.gradient_merge_configs.get("k_steps", 1)) \
            if st.gradient_merge else 1
        self.gm_avg = bool(st.gradient_merge_configs.get("avg", True))
        self.amp_enabled = bool(st.amp)
        # fp16 parity path (reference update_loss_scaling_op.cc +
        # fluid/dygraph/amp/loss_scaler.py): dynamic loss scaling runs
        # INSIDE the compiled step as (scale, good, bad) state.  bf16 is
        # the TPU-native default and needs no scaling.
        self.fp16_scaling = self.amp_enabled and \
            not st.amp_configs.get("use_bf16", True)
        self.amp_dtype = jnp.float16 if self.fp16_scaling else jnp.bfloat16
        ac = st.amp_configs
        self._scaler_cfg = {
            "init_loss_scaling": float(ac.get("init_loss_scaling", 2.**15)),
            "incr_ratio": float(ac.get("incr_ratio", 2.0)),
            "decr_ratio": float(ac.get("decr_ratio", 0.5)),
            "incr_every_n_steps": int(ac.get("incr_every_n_steps", 1000)),
            "decr_every_n_nan_or_inf": int(
                ac.get("decr_every_n_nan_or_inf", 2)),
            # floor for repeated non-finite streaks: dynamic scaling can
            # halve only down to this, never to a denormal/zero scale
            "min_loss_scaling": float(ac.get("min_loss_scaling", 1.0)),
        }
        if self.fp16_scaling and self.k_steps > 1:
            raise NotImplementedError(
                "fp16 loss scaling with gradient_merge (k_steps > 1) is "
                "not supported; use bf16 AMP or k_steps == 1")

        # FLAGS_check_nan_inf coverage for the COMPILED path (reference
        # scans every kernel output, nan_inf_utils_detail.cc:293; here
        # the jitted step returns one bool per checked tensor and the
        # host raises with the offending names).  Read at build time:
        # the flag changes the compiled program.
        from ..core.flags import GLOBAL_FLAGS
        self._check_nan_inf = bool(GLOBAL_FLAGS.get("check_nan_inf"))

        # ---- anomaly policy (resilience): what a non-finite loss/grad
        # does to the step.  "raise" keeps the historical behavior (the
        # nan guard above, only when FLAGS_check_nan_inf is on);
        # "skip" compiles the fp16 scaler's sel(new, old) machinery into
        # the fp32/bf16 step — the bad batch's update is discarded and an
        # on-device counter records it; "rollback" restores the last-good
        # host snapshot and skips the offending batch (host-side, costs
        # one sync per step + a snapshot every rollback_every good steps).
        self.anomaly_policy = (anomaly_policy or
                               os.environ.get("PADDLE_TPU_ANOMALY_POLICY")
                               or "raise")
        if self.anomaly_policy not in ("raise", "skip", "rollback"):
            raise ValueError(
                f"anomaly_policy must be raise|skip|rollback, got "
                f"{self.anomaly_policy!r}")
        if self.anomaly_policy == "rollback" and (
                self.fp16_scaling or self.k_steps > 1):
            raise NotImplementedError(
                "anomaly_policy='rollback' is not supported with fp16 "
                "loss scaling or gradient_merge; use 'skip' (fp16 "
                "already skips overflowed steps)")
        if self.anomaly_policy != "raise":
            # the policy owns non-finite handling; the raise-only guard
            # would defeat it
            self._check_nan_inf = False
        # fp16's scaler already implements skip; the explicit anomaly
        # state drives the fp32/bf16 paths
        self._anom_skip = (self.anomaly_policy == "skip" and
                           not self.fp16_scaling)
        self._anom_rollback = self.anomaly_policy == "rollback"
        if self._anom_rollback:
            # rollback must be able to re-materialize state from its
            # host snapshot at any step; donated buffers + the extra
            # anomaly-vec output mis-alias on cache-deserialized CPU
            # executables (observed: NaN leaking into params two steps
            # after a rollback). The policy already pays a host sync per
            # step — keeping inputs un-donated is the cheap, safe choice.
            self._donate = False
        self._rollback_count = 0
        self._rollback_every = int(os.environ.get(
            "PADDLE_TPU_ROLLBACK_EVERY", "1"))
        self._last_good = None
        # deterministic chaos: poison grads with NaN at step k (compiled
        # into the step; see testing/faults.py)
        from ..testing import faults as _faults
        self._fault_nan_step = _faults.nan_poison_step()

        if st.recompute:
            # model must cooperate (wrap blocks in distributed.recompute);
            # raising here beats silently training without remat
            if not hasattr(model, "enable_recompute"):
                raise NotImplementedError(
                    "strategy.recompute=True but the model has no "
                    "enable_recompute(); wrap blocks with "
                    "paddle_tpu.distributed.recompute(...) instead")
            # honor recompute_configs['policy'] (selective save-dots etc.)
            # defaulting to the unified tuning table's measured winner
            # for this (device, model shape) when one exists (bench.py
            # records the sweep's best remat policy there), then 'full'
            # — full-segment remat, matching the reference's
            # recompute_optimizer; models that predate the policy kwarg
            # keep working (signature-checked, so a TypeError raised
            # INSIDE enable_recompute still propagates)
            import inspect
            pol = st.recompute_configs.get("policy")
            if pol is None:
                pol = tuned_remat_policy(model) or "full"
            sig = inspect.signature(model.enable_recompute)
            if "policy" in sig.parameters:
                model.enable_recompute(policy=pol)
            else:
                model.enable_recompute()

        # quantization-aware training (strategy.qat): every block linear
        # runs the int8/fp8 fake-quant matmul (quantized forward,
        # straight-through backward — ops.quantized_matmul).  One knob:
        # qat_configs={'quantize': 'int8'|'fp8'}.  Params/optimizer are
        # untouched, so every other strategy flag composes.
        if st.qat:
            if not hasattr(model, "enable_quantize"):
                raise NotImplementedError(
                    "strategy.qat=True but the model has no "
                    "enable_quantize(); route its matmuls through "
                    "paddle_tpu.ops.fake_quant_matmul instead")
            model.enable_quantize(st.qat_configs.get("quantize", "int8"))

        # scan-over-layers (recompute_configs={'scan_layers': True}):
        # the model runs its homogeneous block stack as one lax.scan so
        # XLA traces/compiles the body once instead of once per layer;
        # combined with recompute, jax.checkpoint applies per scan
        # iteration (= per block). Independent of strategy.recompute —
        # the compile-time win stands on its own.
        if st.recompute_configs.get("scan_layers"):
            if not hasattr(model, "enable_scan_layers"):
                raise NotImplementedError(
                    "recompute_configs['scan_layers']=True but the model "
                    "has no enable_scan_layers(); only models with a "
                    "homogeneous block stack (GPT) support scanning")
            model.enable_scan_layers(True)

        # ZeRO-3 overlapped all-gather (distributed.zero3): with stage-3
        # sharded params AND a scanned layer stack, the scan prefetches
        # layer i+1's params (explicit all-gather under shard_map) while
        # layer i computes, and grads come back reduce-scattered over dp.
        # sharding_configs={'overlap': False} (or PADDLE_TPU_OVERLAP=0)
        # keeps the synchronous GSPMD stage-3 placement for A/B.
        from .overlap import overlap_enabled
        _ovl = st.sharding_configs.get("overlap") if st.sharding else None
        self.zero3_overlap = bool(
            self.zero_stage >= 3
            and (_ovl if _ovl is not None else overlap_enabled())
            and st.recompute_configs.get("scan_layers")
            and hasattr(model, "enable_zero3_overlap"))
        if self.zero3_overlap:
            model.enable_zero3_overlap(dp_axis)

        # ---- state pytrees (raw arrays keyed by structured name) --------
        self._param_objs = dict(model.named_parameters())
        # name-based decay hooks (AdamW apply_decay_param_fun, Lamb
        # exclude fn) must see Parameter.name in the compiled path too
        optimizer._param_name_map = {
            n: p.name for n, p in self._param_objs.items()}
        optimizer._param_obj_map = dict(self._param_objs)
        params = {n: p.data for n, p in self._param_objs.items()}
        buffers = {n: b.data for n, b in model.named_buffers()
                   if b is not None}
        self._trainable = {n: p.trainable for n, p in
                           self._param_objs.items()}

        # ---- shardings --------------------------------------------------
        dp_in_mesh = dp_axis in self.mesh.axis_names
        self.dp_size = self.mesh.shape[dp_axis] if dp_in_mesh else 1
        # multi-slice (DCN) tier: a "dcn" mesh axis makes the batch
        # shard over ("dcn", dp) — GSPMD then reduces grads ICI-within-
        # slice + DCN-across-slices while params/optimizer state stay
        # per-slice (ZeRO shards inside a slice, replicas across)
        self.dcn_axis = "dcn"
        self.dcn_size = self.mesh.shape[self.dcn_axis] \
            if self.dcn_axis in self.mesh.axis_names else 1
        # membership / in-memory elasticity (attach_membership arms it)
        self.membership = None
        self.dcn_guard = None
        self.reform_in_progress = False
        self._mesh_reforms = 0
        self._lost_slices: list = []
        self._last_reform_info: Optional[dict] = None
        # membership slice id -> current mesh slice row (reforms
        # renumber mesh rows; membership ids are stable)
        self._slice_ids = list(range(self.dcn_size))
        pspecs = build_param_specs(model, self.mesh, dp_axis,
                                   self.zero_stage)
        self._param_specs = pspecs
        self._param_shardings = {
            n: NamedSharding(self.mesh, s) for n, s in pspecs.items()}
        self._buffer_shardings = {
            n: NamedSharding(self.mesh, PartitionSpec()) for n in buffers}
        self._repl = NamedSharding(self.mesh, PartitionSpec())

        # optimizer state: sharded like the param when same-shaped, with
        # ZeRO stage>=1 adding a dp dimension (the reference's
        # sharding_optimizer assigns `param@accumulator` vars to ranks)
        opt_shapes = jax.eval_shape(self.optimizer.init_state, params)

        self._opt_shardings = {
            pname: jax.tree_util.tree_map(
                lambda leaf, pn=pname: self._zero_state_sharding(pn, leaf),
                tree)
            for pname, tree in opt_shapes.items()}

        # place state on the mesh
        self.params = {
            n: jax.device_put(a, self._param_shardings[n])
            for n, a in params.items()}
        self.buffers = {
            n: jax.device_put(a, self._buffer_shardings[n])
            for n, a in buffers.items()}
        with jax.transfer_guard("allow"):
            opt_state = self.optimizer.init_state(self.params)
        self.opt_state = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), opt_state,
            self._opt_shardings)

        # dynamic loss-scale state lives on-device so the whole
        # scale/unscale/check/update state machine compiles into the step
        self._scaler_state = None
        if self.fp16_scaling:
            self._scaler_state = {
                "scale": jax.device_put(jnp.asarray(
                    self._scaler_cfg["init_loss_scaling"], jnp.float32),
                    self._repl),
                "good": jax.device_put(jnp.asarray(0, jnp.int32),
                                       self._repl),
                "bad": jax.device_put(jnp.asarray(0, jnp.int32),
                                      self._repl),
                # optimizer-visible step count: does NOT advance on
                # overflow-skipped steps (the reference skips the whole
                # optimizer call)
                "t": jax.device_put(jnp.asarray(0, jnp.int32),
                                    self._repl),
                "found_inf": jax.device_put(
                    jnp.asarray(False, jnp.bool_), self._repl),
            }
            self._scaler_shardings = {k: self._repl
                                      for k in self._scaler_state}

        # anomaly-skip state lives on-device like the fp16 scaler state:
        # `t` is the optimizer-visible step count (does NOT advance on
        # skipped steps, so Adam bias correction matches a run that never
        # saw the bad batch), `skipped` counts discarded updates
        self._anomaly_state = None
        if self._anom_skip:
            self._anomaly_state = {
                "t": jax.device_put(jnp.asarray(self._step_count,
                                                jnp.int32), self._repl),
                "skipped": jax.device_put(jnp.asarray(0, jnp.int32),
                                          self._repl),
            }
            self._anomaly_shardings = {k: self._repl
                                       for k in self._anomaly_state}

        # gradient-merge buffer (reference GradMergeAllReduceOpHandle /
        # gradient_merge_optimizer.py): ZeRO stage>=2 shards it over dp
        self._grad_buf = None
        if self.k_steps > 1:
            self._grad_shardings = {
                n: self._grad_buf_sharding(n) for n in self.params}
            self._grad_buf = {
                n: jax.device_put(jnp.zeros_like(a),
                                  self._grad_shardings[n])
                for n, a in self.params.items()}

        self._compiled: Dict[str, Any] = {}

        # executable observatory + HBM ledger (ISSUE 15): the trainer's
        # compiled step(s) join the process exec registry under this
        # component label (see _timed_call), and the resident training
        # state — params, optimizer state, buffers, grad-merge buffer —
        # is tracked in the ledger (host-side shape math; weakref'd so
        # a torn-down bench candidate releases its accounting with its
        # HBM)
        self.telemetry_label = f"s{next(_TRAINER_IDS)}"
        self._exec_component = f"trainer:{self.telemetry_label}"
        _exec_registry.track_bytes(
            self, "params", self.telemetry_label,
            _exec_registry.tree_bytes(self.params))
        _exec_registry.track_bytes(
            self, "opt_state", self.telemetry_label,
            _exec_registry.tree_bytes(self.opt_state))
        if self.buffers:
            _exec_registry.track_bytes(
                self, "buffers", self.telemetry_label,
                _exec_registry.tree_bytes(self.buffers))
        if self._grad_buf is not None:
            _exec_registry.track_bytes(
                self, "grad_buffer", self.telemetry_label,
                _exec_registry.tree_bytes(self._grad_buf))

    # ------------------------------------------------------------------
    def _zero_state_sharding(self, pname, leaf):
        """Sharding for one optimizer-state leaf: like the param when
        same-shaped (ZeRO stage>=1 adds a dp dimension), replicated
        otherwise.  Used at build time (on eval_shape structs) and by
        the mesh-reform rebind (on live arrays)."""
        pshape = tuple(self._param_objs[pname].data.shape)
        if tuple(leaf.shape) == pshape:
            base = self._param_specs[pname]
            if self.zero_stage >= 1:
                return NamedSharding(self.mesh, zero_sharding_spec(
                    pshape, base, self.dp_axis, self.dp_size))
            return NamedSharding(self.mesh, base)
        return self._repl

    def _grad_buf_sharding(self, n):
        """Sharding of the gradient-merge buffer for param `n` (ZeRO
        stage>=2 shards it over dp)."""
        if self.zero_stage >= 2:
            return NamedSharding(self.mesh, zero_sharding_spec(
                tuple(self._param_objs[n].data.shape),
                self._param_specs[n], self.dp_axis, self.dp_size))
        return self._param_shardings[n]

    def _batch_sharding(self, arr):
        # dim 0: hierarchical DP when a dcn axis is live — the batch
        # shards over ("dcn", dp), which is what makes GSPMD emit the
        # ICI-within-slice + DCN-across-slices gradient reduce; a batch
        # only divisible by dp falls back to per-slice DP (replicated
        # across slices: consistent, just not hierarchical)
        d0_total = self.dcn_size * self.dp_size
        if (self.dcn_size > 1 and self.dp_size > 1 and arr.ndim > 0
                and arr.shape[0] % d0_total == 0):
            d0 = (self.dcn_axis, self.dp_axis)
        elif (self.dcn_size > 1 and self.dp_size == 1 and arr.ndim > 0
                and arr.shape[0] % self.dcn_size == 0):
            d0 = self.dcn_axis
        elif (self.dp_size > 1 and arr.ndim > 0 and
                arr.shape[0] % self.dp_size == 0):
            d0 = self.dp_axis
        else:
            d0 = None
        dims = [d0]
        # sequence/context parallelism: dim 1 shards over the sp axis
        # (ring attention consumes the blocks; everything else is
        # GSPMD-local)
        sp = self.sp_axis
        sp_size = self.mesh.shape.get(sp, 1) \
            if sp in self.mesh.axis_names else 1
        if arr.ndim > 1:
            dims.append(sp if (sp_size > 1 and
                               arr.shape[1] % sp_size == 0) else None)
        dims += [None] * max(0, arr.ndim - len(dims))
        return NamedSharding(self.mesh, PartitionSpec(*dims))

    def shard_batch(self, batch):
        """Host batch -> device arrays sharded over 'dp' on dim 0 (the
        reference fed per-device scopes; one device_put here).

        Thread-safe and donation-safe: produces fresh committed arrays
        that never alias trainer state, so a DevicePrefetcher may call
        it from a background thread while the step runs.  Leaves that
        are ALREADY committed with the right sharding (a prefetched
        batch re-entering train_step) pass through untouched."""
        t0 = time.perf_counter()

        def put(x):
            arr = x.data if isinstance(x, Tensor) else x
            if isinstance(arr, jax.Array):
                sh = self._batch_sharding(arr)
                if getattr(arr, "sharding", None) == sh and \
                        getattr(arr, "committed", False):
                    return arr  # already placed (device prefetch path)
                return jax.device_put(arr, sh)
            arr = jnp.asarray(arr)
            return jax.device_put(arr, self._batch_sharding(arr))

        out = jax.tree_util.tree_map(
            put, batch, is_leaf=lambda x: isinstance(x, Tensor))
        dt = (time.perf_counter() - t0) * 1e3
        with self._timings_lock:
            self._timings["h2d_ms"] += dt
        tr = _spans.tracer()
        if tr.active:
            now = tr.now_us()
            tr.complete("h2d", now - dt * 1e3, dt * 1e3, cat="train")
        return out

    def _analyze_comm(self, key, args):
        """Collective breakdown of this key's executable (opt-in; one
        AOT lower+compile per executable, done on the FIRST call while
        the args are still alive — the real call may donate them)."""
        from ..utils import comm_stats as _cs
        ss = self.mesh.devices.size // self.dcn_size \
            if self.dcn_size > 1 else None
        res = _cs.analyze_jit(self._compiled[key], *args,
                              device=self.mesh.devices.flat[0],
                              slice_size=ss)
        if res is not None:
            self._comm[key] = res

    def _timed_call(self, key, *args, count_step=True):
        """Invoke a compiled executable, splitting wall time into the
        first call (compile/deserialize) vs steady-state dispatch.
        count_step=False folds the call into dispatch_ms without
        advancing steps_timed (the gradient-merge 'update' executable:
        its cost amortizes over the window, so dispatch_ms/steps_timed
        stays a truthful per-train_step figure)."""
        if key not in self._first_call_keys:
            if self._comm_enabled:
                self._analyze_comm(key, args)
            if _exec_registry.enabled():
                # join the executable observatory at compile time: the
                # arg shape structs are captured pre-call (the step may
                # donate params/opt_state), the XLA cost/memory
                # analysis stays deferred to exec_registry.analyze
                fam = key[0] if isinstance(key, tuple) else str(key)
                _exec_registry.register(
                    self._exec_component, key,
                    _EXEC_KINDS.get(fam, str(fam)),
                    jitfn=self._compiled[key], args=args,
                    donate_argnums=(0, 1) if fam != "eval" else (),
                    meta={"mesh_axes": dict(self.mesh.shape),
                          "zero_stage": self.zero_stage,
                          "amp": self.amp_enabled})
        t0 = time.perf_counter()
        res = self._compiled[key](*args)
        dt = (time.perf_counter() - t0) * 1e3
        if key in self._first_call_keys:
            self._timings["dispatch_ms"] += dt
            if count_step:
                self._timings["steps_timed"] += 1
            _exec_registry.note_runtime(self._exec_component, key, dt)
        else:
            self._first_call_keys.add(key)
            self._timings["compile_ms_cold"] += dt
            _exec_registry.registry().note_compile(
                self._exec_component, key, dt)
        tr = _spans.tracer()
        if tr.active:
            now = tr.now_us()
            tr.complete("dispatch", now - dt * 1e3, dt * 1e3, cat="train",
                        args={"key": str(key)})
        return res

    # ------------------------------------------------------------------
    def _loss_and_buffers(self, params, buffers, inputs, labels,
                          scale=None):
        from ..core.autograd import no_grad
        if self.amp_enabled:
            # cast params AND floating inputs: with fp32 activations JAX
            # type promotion would silently run every matmul in fp32 and
            # AMP would buy nothing (labels/int inputs stay untouched)
            cast = self.amp_dtype
            params = jax.tree_util.tree_map(
                lambda a: a.astype(cast) if _is_floating(a) else a, params)
            inputs = tuple(
                a.astype(cast) if hasattr(a, "dtype") and _is_floating(a)
                else a for a in inputs)
        # the eager tape is bypassed during tracing (jax.grad differentiates
        # the traced ops; recording GradNodes here would only slow compiles)
        from .moe import collect_aux_losses
        with no_grad(), collect_aux_losses() as aux:
            out, new_buffers = functional_call(
                self.model, params, buffers, *inputs, training=True)
        out_t = jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out)
        label_t = [Tensor(l) if not isinstance(l, Tensor) else l
                   for l in labels]
        loss = self.loss_fn(out_t, *label_t)
        loss_arr = loss.data if isinstance(loss, Tensor) else loss
        # router load-balance losses (MoE) ride on top of the task loss
        for a in aux:
            loss_arr = loss_arr + (a.data if isinstance(a, Tensor) else a)
        loss32 = loss_arr.astype(jnp.float32)
        # loss scaling: differentiate the SCALED loss but report the raw
        # one (reference scale->backward->unscale choreography)
        scaled = loss32 * scale if scale is not None else loss32
        return scaled, (new_buffers, out, loss32)

    def _grads_fn(self, params, buffers, inputs, labels,
                  want_outputs=False, scale=None):
        """value_and_grad over trainable params only; frozen params flow
        as constants.  With `scale`, grads come back SCALED (caller
        unscales after the finite check, like check_finite_and_unscale)."""
        train_p = {n: a for n, a in params.items() if self._trainable[n]}
        frozen_p = {n: a for n, a in params.items()
                    if not self._trainable[n]}

        def lfn(tp):
            return self._loss_and_buffers({**tp, **frozen_p}, buffers,
                                          inputs, labels, scale=scale)

        (_, (new_buffers, outs, loss)), grads = jax.value_and_grad(
            lfn, has_aux=True)(train_p)
        grads = {n: grads.get(n, jnp.zeros_like(a))
                 for n, a in params.items()}
        return loss, new_buffers, grads, (outs if want_outputs else None)

    def _apply(self, params, opt_state, grads, lr, step_no):
        new_train, new_state = self.optimizer.apply_gradients(
            {n: a for n, a in params.items() if self._trainable[n]},
            {n: g for n, g in grads.items() if self._trainable[n]},
            {n: s for n, s in opt_state.items() if self._trainable[n]},
            lr=lr, step=step_no)
        new_params = {n: new_train.get(n, a) for n, a in params.items()}
        new_opt = {n: new_state.get(n, s) for n, s in opt_state.items()}
        return new_params, new_opt

    # ------------------------------------------------------------------
    def _nanguard_names(self):
        """Static name list the in-step finite check reports against."""
        return ["loss"] + [f"{n}@GRAD" for n in sorted(self._trainable)
                           if self._trainable[n]]

    def _nanguard_vec(self, loss, grads):
        """One bool per checked tensor: True = contains nan/inf."""
        flags = [~jnp.isfinite(loss)]
        for n in sorted(self._trainable):
            if not self._trainable[n]:
                continue
            g = grads[n]
            if _is_floating(g):
                flags.append(~jnp.all(jnp.isfinite(
                    g.astype(jnp.float32))))
            else:
                flags.append(jnp.asarray(False))
        return jnp.stack(flags)

    def _raise_nonfinite(self, vec, names=None):
        import numpy as _np
        bad = _np.asarray(vec)
        if bad.any():
            names = names or self._nanguard_names()
            names = [n for n, b in zip(names, bad) if b]
            from ..core.errors import PreconditionNotMetError
            raise PreconditionNotMetError(
                f"FLAGS_check_nan_inf: nan/inf detected in compiled "
                f"train step: {names}")

    def _poison_grads(self, grads, step_no):
        """Fault injection (PADDLE_FAULT_NAN_STEP): NaN every floating
        gradient on the armed step. No-op (and nothing compiled in)
        unless armed at trainer build time."""
        k = self._fault_nan_step
        if k is None:
            return grads
        return {n: jnp.where(jnp.asarray(step_no) == k,
                             jnp.full_like(g, jnp.nan), g)
                if _is_floating(g) else g for n, g in grads.items()}

    def _nonfinite_any(self, loss, grads):
        """Scalar bool: loss or any trainable floating grad is nan/inf
        (the skip/rollback policies' trigger)."""
        checks = [jnp.all(jnp.isfinite(g.astype(jnp.float32)))
                  for n, g in grads.items()
                  if self._trainable[n] and _is_floating(g)]
        ok = jnp.stack(checks).all() if checks else jnp.asarray(True)
        return (~jnp.isfinite(loss)) | (~ok)

    # ---- anomaly_policy='rollback' host machinery --------------------
    def _capture_last_good(self):
        """Host-RAM snapshot of the full in-memory training state (the
        rollback target). Must OWN its memory (checkpoint._to_host):
        a zero-copy view would be overwritten by the next donated step
        and the 'last good' snapshot would track the live NaN state."""
        from .checkpoint import _to_host
        self._last_good = {
            "params": _to_host(self.params),
            "opt": _to_host(self.opt_state),
            "buffers": _to_host(self.buffers),
            "step": self._step_count,
        }

    def _restore_last_good(self):
        # device_put of a host array can be ZERO-COPY on the CPU backend;
        # hand it a private copy so the snapshot (which we must be able
        # to restore again) never shares memory with donated live state
        s = self._last_good
        self.params = {
            n: jax.device_put(a.copy(), self._param_shardings[n])
            for n, a in s["params"].items()}
        self.opt_state = jax.tree_util.tree_map(
            lambda a, sh: jax.device_put(a.copy(), sh), s["opt"],
            self._opt_shardings)
        self.buffers = {
            n: jax.device_put(a.copy(), self._buffer_shardings[n])
            for n, a in s["buffers"].items()}
        self._step_count = s["step"]
        self.optimizer._step_count = s["step"]

    def _handle_rollback(self, vec):
        """Host side of anomaly_policy='rollback': on a non-finite step,
        rewind to the last-good snapshot and skip the batch; on a good
        step, refresh the snapshot every rollback_every steps."""
        bad = np.asarray(vec).any()
        if bad:
            self._rollback_count += 1
            # post-mortem FIRST: the bundle must show the state the
            # anomaly was detected in, not the rewound one
            _flightrec.note_event("anomaly_rollback",
                                  step=self._step_count,
                                  rollback_count=self._rollback_count)
            _flightrec.dump("rollback")
            self._restore_last_good()
        elif self._step_count % self._rollback_every == 0:
            self._capture_last_good()
        return bad

    def _build_fused(self, n_inputs, n_labels, with_outputs=False):
        """Single-executable step: fwd+bwd+update (k_steps == 1).
        with_outputs additionally returns the forward outputs (hapi needs
        them for metrics; XLA computes them anyway)."""
        if self.fp16_scaling:
            return self._build_fused_fp16(n_inputs, n_labels, with_outputs)
        anom_skip = self._anom_skip
        want_vec = self._check_nan_inf or self._anom_rollback

        def step(params, opt_state, buffers, *rest):
            if anom_skip:
                anom, lr, step_no = rest[0], rest[1], rest[2]
                batch = rest[3:]
            else:
                anom, (lr, step_no) = None, rest[:2]
                batch = rest[2:]
            inputs, labels = batch[:n_inputs], batch[n_inputs:]
            loss, new_buffers, grads, outs = self._grads_fn(
                params, buffers, inputs, labels, want_outputs=with_outputs)
            grads = self._poison_grads(grads, step_no)
            if anom_skip:
                # fp16-style skip for fp32/bf16: discard the bad batch's
                # update via a scalar select, advance the optimizer step
                # only on finite steps (Adam bias correction parity with
                # a run that never saw the batch)
                bad = self._nonfinite_any(loss, grads)
                t = jnp.where(bad, anom["t"], anom["t"] + 1)
                new_params_u, new_opt_u = self._apply(
                    params, opt_state, grads, lr, t)

                def sel(new, old):
                    return jax.tree_util.tree_map(
                        lambda a, b: jnp.where(bad, b, a), new, old)

                new_params = sel(new_params_u, params)
                new_opt = sel(new_opt_u, opt_state)
                new_anom = {"t": t.astype(jnp.int32),
                            "skipped": (anom["skipped"] +
                                        bad.astype(jnp.int32))}
            else:
                new_params, new_opt = self._apply(
                    params, opt_state, grads, lr, step_no)
                new_anom = None
            merged = dict(buffers)
            merged.update(new_buffers)
            out = (new_params, new_opt, merged, loss)
            if anom_skip:
                out = out + (new_anom,)
            if with_outputs:
                out = out + (outs,)
            if want_vec:
                out = out + (self._nanguard_vec(loss, grads),)
            return out

        donate = ((0, 1, 2, 3) if anom_skip else (0, 1, 2)) \
            if self._donate else ()
        # input shardings come from the committed input arrays (device_put
        # in __init__/shard_batch); out_shardings pin the state placement
        shardings = (self._param_shardings, self._opt_shardings,
                     self._buffer_shardings, self._repl)
        if anom_skip:
            shardings = shardings + (dict(self._anomaly_shardings),)
        if with_outputs:
            shardings = shardings + (None,)  # outputs: let GSPMD place
        if want_vec:
            shardings = shardings + (self._repl,)
        return jax.jit(step, out_shardings=shardings,
                       donate_argnums=donate)

    def _build_fused_fp16(self, n_inputs, n_labels, with_outputs=False):
        """fp16 step with in-graph dynamic loss scaling.

        The whole reference choreography — scale the loss, backward,
        check_finite_and_unscale, conditional optimizer step, scale-state
        update (/root/reference/paddle/fluid/operators/amp/
        update_loss_scaling_op.cc, fluid/dygraph/amp/loss_scaler.py:27) —
        compiles into ONE executable.  Skipping a step is a scalar select
        (no data-dependent control flow; both branches are cheap since
        XLA shares the computed update).  The scaler carries its own step
        counter `t` so Adam bias correction does not advance on skipped
        steps, matching the reference's skipped optimizer call.
        """
        cfg = self._scaler_cfg

        def step(params, opt_state, buffers, scaler, lr, step_no,
                 *batch):
            inputs, labels = batch[:n_inputs], batch[n_inputs:]
            scale = scaler["scale"]
            loss, new_buffers, grads, outs = self._grads_fn(
                params, buffers, inputs, labels,
                want_outputs=with_outputs, scale=scale)
            grads = self._poison_grads(grads, step_no)
            inv = (jnp.asarray(1.0, jnp.float32) / scale)
            grads = {n: g * inv.astype(g.dtype) if _is_floating(g) else g
                     for n, g in grads.items()}
            checks = [jnp.all(jnp.isfinite(g.astype(jnp.float32)))
                      for n, g in grads.items()
                      if self._trainable[n] and _is_floating(g)]
            found_inf = ~jnp.stack(checks).all() if checks \
                else jnp.asarray(False)
            t = jnp.where(found_inf, scaler["t"], scaler["t"] + 1)
            new_params_u, new_opt_u = self._apply(
                params, opt_state, grads, lr, t)

            def sel(new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(found_inf, b, a), new, old)

            new_params = sel(new_params_u, params)
            new_opt = sel(new_opt_u, opt_state)
            # dynamic scale state machine (update_loss_scaling_op.cc):
            # good-step streak doubles the scale every incr_every_n_steps;
            # decr_every_n_nan_or_inf consecutive overflows halve it
            good = jnp.where(found_inf, 0, scaler["good"] + 1)
            bad = jnp.where(found_inf, scaler["bad"] + 1, 0)
            incr = good >= cfg["incr_every_n_steps"]
            decr = bad >= cfg["decr_every_n_nan_or_inf"]
            # keep the old scale if doubling would overflow fp32 (the
            # reference op checks IsFinite(new_scale) the same way —
            # an inf scale would poison every later step)
            grown = scale * cfg["incr_ratio"]
            grown = jnp.where(jnp.isfinite(grown), grown, scale)
            new_scale = jnp.where(incr, grown, scale)
            new_scale = jnp.where(
                decr, jnp.maximum(scale * cfg["decr_ratio"],
                                  jnp.asarray(cfg["min_loss_scaling"],
                                              jnp.float32)),
                new_scale)
            good = jnp.where(incr, jnp.asarray(0, jnp.int32), good)
            bad = jnp.where(decr, jnp.asarray(0, jnp.int32), bad)
            new_scaler = {"scale": new_scale.astype(jnp.float32),
                          "good": good.astype(jnp.int32),
                          "bad": bad.astype(jnp.int32),
                          "t": t.astype(jnp.int32),
                          "found_inf": found_inf}
            merged = dict(buffers)
            merged.update(new_buffers)
            # FLAGS_check_nan_inf under fp16: grad infs are the scaler's
            # legitimate skip signal, but a non-finite UNSCALED loss is a
            # real divergence (log of a negative, etc.) the flag must
            # catch — the scaler would otherwise shrink the scale forever
            extra = ((~jnp.isfinite(loss))[None],) \
                if self._check_nan_inf else ()
            if with_outputs:
                return (new_params, new_opt, merged, loss, new_scaler,
                        outs) + extra
            return (new_params, new_opt, merged, loss,
                    new_scaler) + extra

        donate = (0, 1, 2, 3) if self._donate else ()
        scaler_sh = dict(self._scaler_shardings)
        shardings = (self._param_shardings, self._opt_shardings,
                     self._buffer_shardings, self._repl, scaler_sh)
        if with_outputs:
            shardings = shardings + (None,)
        if self._check_nan_inf:
            shardings = shardings + (self._repl,)
        return jax.jit(step, out_shardings=shardings,
                       donate_argnums=donate)

    def _build_accum(self, n_inputs, n_labels):
        anom_skip = self._anom_skip

        def accum(params, grad_buf, buffers, *rest):
            if anom_skip:
                anom, batch = rest[0], rest[1:]
            else:
                anom, batch = None, rest
            inputs, labels = batch[:n_inputs], batch[n_inputs:]
            loss, new_buffers, grads, _ = self._grads_fn(
                params, buffers, inputs, labels)
            if anom_skip:
                # a poisoned micro-batch is dropped from the window (its
                # grads never enter the merge buffer); the window-end
                # update still divides by k_steps — skip under gradient
                # merge trades a slightly small update for survival
                bad = self._nonfinite_any(loss, grads)
                new_buf = {n: jnp.where(bad, grad_buf[n],
                                        grad_buf[n] + grads[n])
                           for n in grad_buf}
                new_anom = {"t": anom["t"],
                            "skipped": (anom["skipped"] +
                                        bad.astype(jnp.int32))}
            else:
                new_buf = {n: grad_buf[n] + grads[n] for n in grad_buf}
                new_anom = None
            merged = dict(buffers)
            merged.update(new_buffers)
            out = (new_buf, merged, loss)
            if anom_skip:
                out = out + (new_anom,)
            if self._check_nan_inf:
                out = out + (self._nanguard_vec(loss, grads),)
            return out

        donate = ((1, 2, 3) if anom_skip else (1, 2)) \
            if self._donate else ()
        shardings = (self._grad_shardings, self._buffer_shardings,
                     self._repl)
        if anom_skip:
            shardings = shardings + (dict(self._anomaly_shardings),)
        if self._check_nan_inf:
            shardings = shardings + (self._repl,)
        return jax.jit(accum, out_shardings=shardings,
                       donate_argnums=donate)

    def _build_update(self):
        scale = (1.0 / self.k_steps) if self.gm_avg else 1.0

        def update(params, opt_state, grad_buf, lr, step_no):
            grads = {n: g * scale for n, g in grad_buf.items()}
            new_params, new_opt = self._apply(
                params, opt_state, grads, lr, step_no)
            zeroed = {n: jnp.zeros_like(g) for n, g in grad_buf.items()}
            return new_params, new_opt, zeroed

        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(
            update,
            out_shardings=(self._param_shardings, self._opt_shardings,
                           self._grad_shardings),
            donate_argnums=donate)

    def _build_eval(self, n_inputs):
        def fwd(params, buffers, *inputs):
            if self.amp_enabled:
                # cast params AND floating inputs, like the train path —
                # mixed fp32 inputs fail dtype-strict ops (conv) outright
                cast = self.amp_dtype
                params = jax.tree_util.tree_map(
                    lambda a: a.astype(cast) if _is_floating(a) else a,
                    params)
                inputs = tuple(
                    a.astype(cast) if hasattr(a, "dtype") and
                    _is_floating(a) else a for a in inputs)
            out, _ = functional_call(self.model, params, buffers, *inputs,
                                     training=False)
            return out

        return jax.jit(fwd)

    @staticmethod
    def _span_sync(dt_ms: float):
        tr = _spans.tracer()
        if tr.active:
            now = tr.now_us()
            tr.complete("sync", now - dt_ms * 1e3, dt_ms * 1e3,
                        cat="train")

    def _watchdog_beat(self):
        """Arm the stall watchdog on the first step when
        PADDLE_TPU_WATCHDOG_S is set, then heartbeat it: one monotonic
        store per step while armed, one cached None check otherwise."""
        if not self._wd_checked:
            self._wd_checked = True
            t = _watchdog.watchdog_seconds()
            if t is not None:
                self.watchdog = _watchdog.Watchdog(
                    t, label="spmd_train").arm()
        if self.watchdog is not None:
            self.watchdog.beat()

    def _telemetry_step_end(self):
        """Per-step telemetry tail: tick the wall timer and mirror it
        into the metrics registry (and the flight-recorder ring).  Pure
        host arithmetic on pre-bound children — no sync, no allocation
        beyond the timer's float and one bounded ring entry."""
        self.step_timer.tick()
        self._m_steps.inc()
        last = self.step_timer.last_ms
        if last is not None:
            self._m_step_ms.set(last)
            self._m_step_hist.observe(last)
        _flightrec.record("train_step", dur_ms=last,
                          step=self._step_count)
        if self._retuner is not None:
            self._retuner.on_step(last)

    # ---- multi-slice membership / in-memory elasticity ---------------
    def attach_membership(self, membership, guard=None):
        """Arm slice-loss detection (distributed.membership): every
        train_step beats the surviving slices this process hosts (the
        single-process virtual-slice harness; a real multi-host
        deployment beats only its own slice through the file transport)
        and polls the failure detector — a membership change triggers
        the in-memory mesh reform.  `guard` (a DcnCollectiveGuard) is
        adopted for stats and wired into the same membership object,
        so a guard escalation reforms exactly like a heartbeat
        timeout; its backoff waits feed this trainer's stall watchdog.
        """
        self.membership = membership
        self.dcn_guard = guard
        if guard is not None:
            if guard.membership is None:
                guard.membership = membership
            if guard.on_beat is None:
                guard.on_beat = self._watchdog_beat
        return self

    def _membership_tick(self):
        """Step-boundary membership maintenance: beat, poll, and — on a
        membership change — re-form the mesh over the survivors before
        the next step runs."""
        m = self.membership
        if m is None:
            return
        m.beat_all(step=self._step_count)
        m.poll()
        # heartbeat timeouts AND guard escalations both land in
        # dead_slices(); translate stable membership ids to current
        # mesh slice rows (reforms renumber rows, ids persist)
        newly = [sid for sid in sorted(m.dead_slices())
                 if sid in self._slice_ids]
        if newly:
            rows = [self._slice_ids.index(sid) for sid in newly]
            self.reform_mesh(rows, member_ids=newly)

    def reform_mesh(self, lost_rows, member_ids=None):
        """In-memory mid-run elasticity: the current step has finished;
        snapshot the full training state to host (owned copies — the
        donation-safe checkpoint snapshot), re-form the mesh over the
        surviving slices, rebuild every sharding tree against it, and
        re-place the snapshot through the elastic-reshard restore path
        WITHOUT any checkpoint-dir round trip.  Executables re-register
        with the observatory on their first post-reform call; the step
        after that first call is recompile-free again (the
        zero-recompile contract on the new topology).

        lost_rows: indices into the CURRENT mesh's dcn axis.
        member_ids: the stable membership ids those rows carry (for
        stats; defaults to the rows themselves).
        """
        from .checkpoint import restore_trainer, snapshot_trainer
        lost = sorted({int(r) for r in lost_rows})
        if not lost:
            return self
        if self.dcn_size <= 1 or len(lost) >= self.dcn_size:
            raise RuntimeError(
                f"cannot re-form mesh: lost slices {lost} of "
                f"{self.dcn_size} — no survivors")
        ids = sorted(member_ids) if member_ids else lost
        t0 = time.perf_counter()
        self.reform_in_progress = True
        _flightrec.note_event("mesh_reform_begin", lost_slices=ids,
                              step=self._step_count,
                              dcn_from=self.dcn_size)
        try:
            state = snapshot_trainer(self)  # host snapshot, owned copies
            survivors = [r for r in range(self.dcn_size) if r not in lost]
            # the mesh is dcn-major (create_mesh): slice r owns row r of
            # the (dcn, -1) device view
            devs = self.mesh.devices.reshape(self.dcn_size, -1)[survivors]
            axes = {n: int(self.mesh.shape[n])
                    for n in self.mesh.axis_names}
            axes[self.dcn_axis] = len(survivors)
            new_mesh = Mesh(devs.reshape(list(axes.values())),
                            tuple(axes.keys()))
            self._rebind_mesh(new_mesh)
            # the elastic-reshard restore applied to the in-memory
            # snapshot: every leaf is re-placed under the NEW shardings
            # (make_array_from_callback), no disk involved.  elastic is
            # forced — attaching membership IS the opt-in to mid-run
            # topology change, regardless of resume_elastic strictness
            restore_trainer(self, state, elastic=True)
        finally:
            self.reform_in_progress = False
        dur_ms = (time.perf_counter() - t0) * 1e3
        self._mesh_reforms += 1
        self._lost_slices.extend(ids)
        self._slice_ids = [sid for i, sid in enumerate(self._slice_ids)
                           if i not in lost]
        self._last_reform_info = {
            "lost_slices": ids, "dcn_size": self.dcn_size,
            "step": self._step_count, "ms": round(dur_ms, 2)}
        _metrics.counter(
            "mesh_reforms_total",
            "in-memory mesh re-formations after slice loss").inc()
        _metrics.gauge(
            "mesh_reform_ms",
            "last in-memory mesh reform wall time").set(round(dur_ms, 3))
        _flightrec.note_event("mesh_reform", lost_slices=ids,
                              dcn_size=self.dcn_size,
                              step=self._step_count, ms=round(dur_ms, 2))
        return self

    def _rebind_mesh(self, mesh):
        """Rebuild every sharding tree and drop the compiled-executable
        cache for a NEW mesh (the reform path).  State arrays still
        live under the old placement afterwards — the caller re-places
        them (restore_trainer over the host snapshot)."""
        self.mesh = mesh
        self.dp_size = mesh.shape[self.dp_axis] \
            if self.dp_axis in mesh.axis_names else 1
        self.dcn_size = mesh.shape[self.dcn_axis] \
            if self.dcn_axis in mesh.axis_names else 1
        pspecs = build_param_specs(self.model, mesh, self.dp_axis,
                                   self.zero_stage)
        self._param_specs = pspecs
        self._param_shardings = {
            n: NamedSharding(mesh, s) for n, s in pspecs.items()}
        self._buffer_shardings = {
            n: NamedSharding(mesh, PartitionSpec())
            for n in self.buffers}
        self._repl = NamedSharding(mesh, PartitionSpec())
        self._opt_shardings = {
            pname: jax.tree_util.tree_map(
                lambda leaf, pn=pname: self._zero_state_sharding(pn, leaf),
                tree)
            for pname, tree in self.opt_state.items()}
        if self._scaler_state is not None:
            self._scaler_shardings = {k: self._repl
                                      for k in self._scaler_state}
        if self._anomaly_state is not None:
            self._anomaly_shardings = {k: self._repl
                                       for k in self._anomaly_state}
        if self._grad_buf is not None:
            self._grad_shardings = {
                n: self._grad_buf_sharding(n) for n in self.params}
        # new mesh => new executables: drop the compiled cache so the
        # first post-reform call compiles once, and clear the first-call
        # markers so compile-vs-dispatch attribution and exec-registry
        # re-registration behave like a fresh trainer
        self._compiled.clear()
        self._first_call_keys.clear()
        self._comm.clear()

    # ------------------------------------------------------------------
    def train_step(self, inputs, labels, return_outputs=False):
        """Run one compiled training step. inputs/labels: array, Tensor,
        or tuple thereof. Returns a lazy StepResult (no host sync — the
        device scalar is fetched, once, when you float()/read it; until
        then the host keeps dispatching ahead of the device); with
        return_outputs=True returns (StepResult, outputs) — the forward
        outputs ride along for metric computation (hapi)."""
        from . import env as _env
        _env.heartbeat()  # launcher watchdog liveness (no-op if unset)
        self._watchdog_beat()  # stall monitor (PADDLE_TPU_WATCHDOG_S)
        if self._profile is not None:
            # PADDLE_TPU_PROFILE=start:stop — device capture windowed on
            # the step counter (observability.capture)
            self._profile.on_step(self._step_count)
        inputs = inputs if isinstance(inputs, (tuple, list)) else (inputs,)
        labels = labels if isinstance(labels, (tuple, list)) else (labels,)
        batch = self.shard_batch(tuple(inputs) + tuple(labels))
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)

        if self.k_steps == 1:
            key = ("fused_out" if return_outputs else "fused",
                   len(inputs), len(labels))
            if key not in self._compiled:
                self._compiled[key] = self._build_fused(
                    len(inputs), len(labels), with_outputs=return_outputs)
            step_no = jnp.asarray(self._step_count + 1, jnp.int32)
            if self._anom_rollback and self._last_good is None:
                self._capture_last_good()  # rollback target before step 1
            # the ambient mesh lets layers place sharding constraints on
            # intermediates (MoE dispatch buffers) while jit traces
            with compile_mesh_guard(self.mesh):
                if self.fp16_scaling:
                    res = self._timed_call(
                        key, self.params, self.opt_state, self.buffers,
                        self._scaler_state, lr, step_no, *batch)
                elif self._anom_skip:
                    res = self._timed_call(
                        key, self.params, self.opt_state, self.buffers,
                        self._anomaly_state, lr, step_no, *batch)
                else:
                    res = self._timed_call(
                        key, self.params, self.opt_state, self.buffers,
                        lr, step_no, *batch)
            res = list(res)
            guard = res.pop() \
                if (self._check_nan_inf or self._anom_rollback) else None
            outs = res.pop() if return_outputs else None
            if self.fp16_scaling:
                (self.params, self.opt_state, self.buffers, loss,
                 self._scaler_state) = res
            elif self._anom_skip:
                (self.params, self.opt_state, self.buffers, loss,
                 self._anomaly_state) = res
            else:
                self.params, self.opt_state, self.buffers, loss = res
            self._step_count += 1
            self.optimizer._step_count = self._step_count
            if self._anom_rollback:
                # one host sync per step — the policy's documented price
                t_sync = time.perf_counter()
                self._handle_rollback(guard)
                async_dispatch.record_host_sync()
                dt_sync = (time.perf_counter() - t_sync) * 1e3
                self._timings["sync_ms"] += dt_sync
                self._span_sync(dt_sync)
            elif guard is not None:
                t_sync = time.perf_counter()
                self._raise_nonfinite(
                    guard, names=["loss"] if self.fp16_scaling else None)
                async_dispatch.record_host_sync()
                dt_sync = (time.perf_counter() - t_sync) * 1e3
                self._timings["sync_ms"] += dt_sync
                self._span_sync(dt_sync)
            from ..testing import faults as _faults
            _faults.maybe_sigterm(self._step_count)
            _faults.maybe_hang(self._step_count)
            self._telemetry_step_end()
            self._membership_tick()
            result = StepResult(loss, timings=self._timings, outputs=outs)
            return (result, outs) if return_outputs else result
        if return_outputs:
            raise NotImplementedError(
                "return_outputs with gradient merge (k_steps > 1) is not "
                "supported; drop metrics or gradient_merge")

        akey = ("accum", len(inputs), len(labels))
        if akey not in self._compiled:
            self._compiled[akey] = self._build_accum(
                len(inputs), len(labels))
        if "update" not in self._compiled:
            self._compiled["update"] = self._build_update()
        with compile_mesh_guard(self.mesh):
            if self._anom_skip:
                res = self._timed_call(
                    akey, self.params, self._grad_buf, self.buffers,
                    self._anomaly_state, *batch)
            else:
                res = self._timed_call(
                    akey, self.params, self._grad_buf, self.buffers,
                    *batch)
        res = list(res)
        guard = res.pop() if self._check_nan_inf else None
        if self._anom_skip:
            self._grad_buf, self.buffers, loss, self._anomaly_state = res
        else:
            self._grad_buf, self.buffers, loss = res
        self._step_count += 1
        if guard is not None:
            t_sync = time.perf_counter()
            self._raise_nonfinite(guard)
            async_dispatch.record_host_sync()
            self._timings["sync_ms"] += (time.perf_counter() - t_sync) * 1e3
        if self._step_count % self.k_steps == 0:
            step_no = jnp.asarray(
                self._step_count // self.k_steps, jnp.int32)
            self.params, self.opt_state, self._grad_buf = \
                self._timed_call(
                    "update", self.params, self.opt_state, self._grad_buf,
                    lr, step_no, count_step=False)
            self.optimizer._step_count = self._step_count // self.k_steps
        from ..testing import faults as _faults
        _faults.maybe_sigterm(self._step_count)
        _faults.maybe_hang(self._step_count)
        self._telemetry_step_end()
        self._membership_tick()
        return StepResult(loss, timings=self._timings)

    def eval_step(self, inputs):
        # an eval loop is progress too: heartbeat (never arm — an
        # eval-only user has no step loop to watch), so a post-training
        # evaluation phase neither false-fires nor goes unwatched
        if self.watchdog is not None:
            self.watchdog.beat()
        inputs = inputs if isinstance(inputs, (tuple, list)) else (inputs,)
        batch = self.shard_batch(tuple(inputs))
        key = ("eval", len(inputs))
        if key not in self._compiled:
            self._compiled[key] = self._build_eval(len(inputs))
        with compile_mesh_guard(self.mesh):
            return self._compiled[key](self.params, self.buffers, *batch)

    predict_step = eval_step

    # ------------------------------------------------------------------
    def sync_to_model(self):
        """Write trainer-owned arrays back into the model's Tensors (for
        checkpointing / eager inspection). Reference analogue: fetching
        persistables out of the ParallelExecutor's scopes."""
        for n, p in self._param_objs.items():
            p._data = self.params[n]
        buf_objs = dict(self.model.named_buffers())
        for n, a in self.buffers.items():
            if n in buf_objs and buf_objs[n] is not None:
                buf_objs[n]._data = a
        return self.model

    def sync_from_model(self):
        """Adopt the model's current Tensor values as the trainer state
        (after a checkpoint load into the model) — the reverse of
        sync_to_model; re-places every array with its mesh sharding."""
        self.params = {
            n: jax.device_put(jnp.asarray(p.data),
                              self._param_shardings[n])
            for n, p in self._param_objs.items()}
        buf_objs = dict(self.model.named_buffers())
        self.buffers = {
            n: jax.device_put(jnp.asarray(buf_objs[n].data),
                              self._buffer_shardings[n])
            if n in buf_objs and buf_objs[n] is not None else a
            for n, a in self.buffers.items()}
        return self

    def state_dict(self):
        sd = {n: Tensor(a) for n, a in self.params.items()}
        sd.update({n: Tensor(a) for n, a in self.buffers.items()})
        return sd

    def save(self, path: str, extra=None, manifest: bool = False) -> str:
        """Checkpoint the full training state (params + opt state + step
        + LR scheduler [+ grad-merge buffer, scaler, anomaly counters]) —
        reference auto_checkpoint.py:71 / fleet.save_persistables.
        manifest=True writes the integrity-checked directory format
        (sha256-verified on load; see distributed/resilience.py for the
        async keep-last-K manager built on it)."""
        from .checkpoint import save_trainer
        return save_trainer(self, path, extra=extra, manifest=manifest)

    def load(self, path: str) -> dict:
        """Restore a save() checkpoint (single-file or manifest dir);
        shardings are re-applied from THIS trainer, so the mesh layout
        may differ from the writer's."""
        from .checkpoint import load_trainer
        return load_trainer(self, path)

    def export_train_step(self, path: str, example_inputs,
                          example_labels) -> str:
        """Serialize the WHOLE fused train step (fwd+bwd+update) as
        StableHLO + initial state — the artifact a non-Python runtime
        (inference/capi trainer entry) drives for native training, the
        TPU-native answer to the reference's C++ train demo
        (fluid/train/demo: load a program with backward ops and run it).
        """
        import pickle
        from jax import export as jexport
        if self.fp16_scaling or self._check_nan_inf or \
                self.anomaly_policy != "raise":
            raise NotImplementedError(
                "export_train_step supports the standard bf16/fp32 step "
                "(no fp16 scaler state, no nan guard, no anomaly policy) "
                "for a stable serialized signature")
        inputs = example_inputs if isinstance(example_inputs,
                                              (tuple, list)) \
            else (example_inputs,)
        labels = example_labels if isinstance(example_labels,
                                              (tuple, list)) \
            else (example_labels,)
        batch = self.shard_batch(tuple(inputs) + tuple(labels))
        # a fresh non-donating jit: donation has no meaning across the
        # serialization boundary
        saved_donate, self._donate = self._donate, False
        try:
            step = self._build_fused(len(inputs), len(labels))
        finally:
            self._donate = saved_donate

        def aval(a):
            return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

        with compile_mesh_guard(self.mesh):
            exported = jexport.export(step)(
                jax.tree_util.tree_map(aval, self.params),
                jax.tree_util.tree_map(aval, self.opt_state),
                jax.tree_util.tree_map(aval, self.buffers),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.int32),
                *[aval(b) for b in batch])
        import os as _os
        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".pdtrain", "wb") as f:
            f.write(exported.serialize())
        state = {
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "opt_state": jax.tree_util.tree_map(np.asarray,
                                                self.opt_state),
            "buffers": jax.tree_util.tree_map(np.asarray, self.buffers),
            "lr": float(self.optimizer.get_lr()),
            "step_count": self._step_count,
        }
        with open(path + ".pdtrainstate", "wb") as f:
            pickle.dump(state, f, protocol=4)
        return path

    @property
    def stats(self) -> dict:
        """Resilience counters + step-time breakdown for logging/bench.

        Anomaly half: the active policy plus how many updates it
        discarded (skip: on-device counter; fp16: steps whose
        optimizer-visible count did not advance; rollback: host rewinds).
        Reading the on-device counters is itself a host sync — call this
        at log boundaries, not per step.

        Timing half (milliseconds, cumulative since construction):
        ``data_wait_ms`` (consumer blocked on the prefetch queue),
        ``h2d_ms`` (host spent placing batches), ``dispatch_ms``
        (steady-state compiled-step calls), ``sync_ms`` (blocked host
        read-backs), ``compile_ms_cold`` (first-call compile/deserialize
        cost per executable), ``steps_timed``."""
        s = {"anomaly_policy": self.anomaly_policy,
             "rollback_steps": self._rollback_count,
             "resume_elastic": self.resume_elastic,
             "reshard_restores": self._reshard_restores,
             # multi-slice tier: how many in-memory reforms ran, which
             # membership slice ids were lost, and the live dcn extent
             "mesh_reforms": self._mesh_reforms,
             "lost_slices": list(self._lost_slices),
             "dcn_slices": self.dcn_size}
        if self._last_reform_info is not None:
            s["last_reform"] = dict(self._last_reform_info)
        if self.membership is not None:
            ms = self.membership.stats()
            s["slice_heartbeat_ages"] = ms["heartbeat_ages"]
            s["slice_timeout_s"] = ms["timeout_s"]
            s["slices_dead"] = ms["dead"]
        if self.dcn_guard is not None:
            s["dcn_guard"] = self.dcn_guard.stats()
        t_sync = time.perf_counter()
        if self._anomaly_state is not None:
            s["skipped_steps"] = int(self._anomaly_state["skipped"])
            async_dispatch.record_host_sync()
        elif self.fp16_scaling and self._scaler_state is not None:
            s["skipped_steps"] = int(
                self._step_count - int(self._scaler_state["t"]))
            async_dispatch.record_host_sync()
        else:
            s["skipped_steps"] = 0
        self._timings["sync_ms"] += (time.perf_counter() - t_sync) * 1e3
        for k, v in self._timings.items():
            s[k] = round(v, 3) if isinstance(v, float) else v
        # per-step wall clock (profiler.StepTimer, warmup-excluded):
        # step_time_ms is the figure hapi logs; mean/p50 summarize
        s["step_time_ms"] = round(self.step_timer.last_ms, 3) \
            if self.step_timer.last_ms is not None else None
        s["step_time_mean_ms"] = round(self.step_timer.mean_ms, 3) \
            if self.step_timer.mean_ms is not None else None
        s["step_time_p50_ms"] = round(self.step_timer.p50_ms, 3) \
            if self.step_timer.p50_ms is not None else None

        # collective breakdown (PADDLE_TPU_COMM_STATS / comm_stats=True):
        # per-step bytes each compiled step moves over the interconnect
        # and the bandwidth-model transfer time; comm_fraction divides
        # that by the MEASURED mean step time, so an overlap schedule
        # that actually hides its collectives shows the fraction shrink
        # instead of the step time growing
        comm_ms = comm_bytes = comm_count = 0.0
        comm_ici = comm_dcn = 0.0
        comm_split = False
        by_op: Dict[str, dict] = {}
        # one per-step executable counts (the most recently analyzed
        # fused/accum variant — 'fused' and 'fused_out' are the SAME
        # step, summing both would double the figure); the gradient-
        # merge 'update' amortizes over its window
        step_keys = [k for k in self._comm
                     if k == "update" or k[0] in ("fused", "fused_out",
                                                  "accum")]
        per_step = [k for k in step_keys if k != "update"]
        chosen = ([per_step[-1]] if per_step else []) + \
            (["update"] if "update" in self._comm else [])
        for key in chosen:
            res = self._comm[key]
            scale = 1.0 / self.k_steps if key == "update" else 1.0
            comm_ms += res["comm_ms"] * scale
            comm_bytes += res["bytes"] * scale
            comm_count += res["count"] * scale
            if "dcn_bytes" in res:
                comm_split = True
                comm_ici += res["ici_bytes"] * scale
                comm_dcn += res["dcn_bytes"] * scale
            for op, v in res["by_op"].items():
                slot = by_op.setdefault(op, {"count": 0.0, "bytes": 0.0})
                slot["count"] += v["count"] * scale
                slot["bytes"] += v["bytes"] * scale
                if "dcn_bytes" in v:
                    slot["ici_bytes"] = slot.get("ici_bytes", 0.0) \
                        + v["ici_bytes"] * scale
                    slot["dcn_bytes"] = slot.get("dcn_bytes", 0.0) \
                        + v["dcn_bytes"] * scale
        s["comm_ms"] = round(comm_ms, 4) if self._comm else None
        s["comm_bytes"] = int(comm_bytes) if self._comm else None
        s["comm_collectives"] = int(comm_count) if self._comm else None
        s["comm_by_op"] = by_op if self._comm else None
        # ici/dcn byte split (multi-slice meshes with comm stats on):
        # the evidence for the dcn-bound doctor rule and the dcn phase
        s["comm_bytes_ici"] = int(comm_ici) if comm_split else None
        s["comm_bytes_dcn"] = int(comm_dcn) if comm_split else None
        steps = self._timings["steps_timed"]
        mean_step = (self._timings["dispatch_ms"] / steps) if steps else 0.0
        s["comm_fraction"] = round(comm_ms / mean_step, 4) \
            if (self._comm and mean_step > 0) else None
        # executable observatory (ISSUE 15): per-kind roofline digest
        # for this trainer's executables — populated once the deferred
        # analyses ran (bench, report CLI, exec_registry.analyze_all).
        # Reading stats never compiles.
        s["exec_profile"] = _exec_registry.profile(self._exec_component)
        s["hbm"] = _exec_registry.ledger().snapshot()
        # perf-doctor verdict over everything above (observability.
        # doctor): ranked [{bottleneck, evidence, knob}] — host-side
        # dict math, the machine-readable half of the ROADMAP-1 triage
        s["doctor"] = _doctor.diagnose(s, kind="train")
        return s

    @property
    def loss_scale(self):
        """Current dynamic loss scale (None unless fp16 AMP)."""
        if self._scaler_state is None:
            return None
        return float(self._scaler_state["scale"])

    @property
    def last_step_skipped(self):
        """True when the previous fp16 step hit inf/nan and was skipped."""
        if self._scaler_state is None:
            return False
        return bool(self._scaler_state["found_inf"])

    @property
    def step_executable(self):
        """The underlying compiled step (for introspection/tests)."""
        for k in ("fused", "accum"):
            for key, v in self._compiled.items():
                if key[0] == k:
                    return v
        return None


def dp_train_step(model: Layer, optimizer, loss_fn,
                  mesh: Optional[Mesh] = None, **kwargs):
    """Convenience promised by distributed.parallel: build an SpmdTrainer
    on a dp mesh and return (trainer, trainer.train_step)."""
    trainer = SpmdTrainer(model, optimizer, loss_fn, mesh=mesh, **kwargs)
    return trainer, trainer.train_step
