"""Device mesh management.

Reference mapping (SURVEY.md §5/§7): the reference's ring_id->NCCLComm
registry (collective_helper.h:65) + per-parallel-dimension rings
(sharding/dp/pp pairs, pipeline_optimizer.py:136) become ONE
jax.sharding.Mesh with named axes; a "ring" is just a mesh axis name, and
XLA lowers collectives over the right ICI links from the device
assignment. Axis-name conventions used across the framework:

    dp - data parallel          tp - tensor model parallel
    pp - pipeline stages        sp - sequence/context parallel
    ep - expert parallel        dcn - data-parallel across slices

The `dcn` axis is the multi-slice tier: devices within one slice talk
over ICI, slices talk over the (much slower) data-center network.
`create_mesh(..., dcn_slices=N)` (or PADDLE_TPU_DCN_SLICES=N) prepends
a dcn axis of size N, and sharding the batch over ("dcn", "dp") makes
GSPMD emit the hierarchical gradient reduce: ICI all-reduce within a
slice, DCN all-reduce across slices.
"""
from __future__ import annotations

import contextlib
import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# shard_map moved across jax versions (jax.experimental.shard_map ->
# top-level jax.shard_map) and renamed its replication-check kwarg
# (check_rep -> check_vma); resolve once here so every consumer gets a
# callable with the NEW spelling regardless of the installed version.
try:
    from jax import shard_map as _sm
    _shard_map = _sm if callable(_sm) else _sm.shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

if "check_vma" in _inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    import functools as _functools

    @_functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

def axis_size(axis_name: str) -> int:
    """Static size of a bound mesh axis inside shard_map/pmap bodies.
    jax.lax.axis_size only exists from jax 0.5; psum of a Python
    constant is the portable spelling (folded to a static int)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


# ---------------------------------------------------------------------------
# Version-compat collective helpers (used inside shard_map bodies).
#
# spmd/pipeline/moe/ring_attention each used to spell these against
# jax.lax directly; the names and kwargs moved across jax versions
# (psum_scatter's `scatter_dimension`, all_gather's `tiled` default), so
# one shim here keeps every schedule on the same spelling.  All three
# return the TILED layout: gather concatenates shards on `axis`,
# reduce_scatter leaves each rank its `axis` slice of the sum.
# ---------------------------------------------------------------------------
def all_gather(x, axis_name: str, *, axis: int = 0):
    """Concatenate every rank's shard along `axis` (tiled all-gather)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reduce_scatter(x, axis_name: str, *, axis: int = 0):
    """Sum over the axis group and keep this rank's `axis` slice — the
    transpose of `all_gather`, and the collective ZeRO grads leave the
    backward as."""
    if hasattr(jax.lax, "psum_scatter"):
        return jax.lax.psum_scatter(x, axis_name,
                                    scatter_dimension=axis, tiled=True)
    # very old jax: psum + per-rank dynamic slice (correct, not bandwidth
    # optimal — only a fallback)
    n = axis_size(axis_name)
    if x.shape[axis] % n:
        # psum_scatter would raise here; the fallback must not silently
        # truncate the trailing rows instead
        raise ValueError(
            f"reduce_scatter: dim {axis} of shape {x.shape} is not "
            f"divisible by axis '{axis_name}' size {n}")
    full = jax.lax.psum(x, axis_name)
    idx = jax.lax.axis_index(axis_name)
    shard = x.shape[axis] // n
    return jax.lax.dynamic_slice_in_dim(full, idx * shard, shard, axis)


def ppermute(x, axis_name: str, perm):
    """Point-to-point send/recv over the axis ring (pipeline stage
    boundaries). perm: [(src, dst), ...]; unaddressed dsts receive
    zeros."""
    return jax.lax.ppermute(x, axis_name, perm)


__all__ = ["Mesh", "NamedSharding", "PartitionSpec", "axis_size",
           "all_gather", "reduce_scatter", "ppermute",
           "create_mesh", "get_mesh", "set_mesh", "mesh_axis_size",
           "default_mesh", "shard_map", "dcn_slice_count", "slice_size"]

_current_mesh: Optional[Mesh] = None


def create_mesh(axes: Union[Dict[str, int], Sequence[int]],
                axis_names: Optional[Sequence[str]] = None,
                devices=None,
                dcn_slices: Optional[int] = None) -> Mesh:
    """Build a Mesh from {'dp': 2, 'tp': 4} style spec. -1 for one axis
    means 'all remaining devices'.

    dcn_slices=N (or PADDLE_TPU_DCN_SLICES=N) prepends a "dcn" axis of
    size N — the mesh becomes N slices of equal shape, dcn-major in
    device order (slice s owns `devices.reshape(N, -1)[s]`), so ICI
    collectives group within a slice and dcn-axis collectives cross
    slices. A spec that already names a "dcn" axis wins over both.
    """
    if isinstance(axes, dict):
        names = list(axes.keys())
        shape = list(axes.values())
    else:
        shape = list(axes)
        names = list(axis_names or [f"axis{i}" for i in range(len(shape))])
    if dcn_slices is None:
        env = os.environ.get("PADDLE_TPU_DCN_SLICES", "").strip()
        if env:
            try:
                dcn_slices = int(env)
            except ValueError:
                dcn_slices = None
    if dcn_slices is not None and int(dcn_slices) >= 1 and "dcn" not in names:
        names = ["dcn"] + names
        shape = [int(dcn_slices)] + shape
    devs = np.asarray(devices if devices is not None else jax.devices())
    # deterministic chaos (PADDLE_FAULT_MESH_SHRINK): the scheduler
    # handed back fewer chips — build the mesh from the survivors only,
    # so elastic-restore tests exercise a real topology change without
    # re-execing under a different device-count flag
    from ..testing import faults as _faults
    _shrink = _faults.mesh_shrink()
    if _shrink is not None and _shrink < devs.size:
        n_dcn = shape[names.index("dcn")] if "dcn" in names else 0
        if n_dcn > 0:
            # multi-slice clamp at whole-slice granularity: a ragged
            # slice (half its chips gone) can't host its shard of the
            # per-slice axes, so the survivors are the largest whole
            # number of slices that fit under the clamp — the dcn
            # extent shrinks, every surviving slice stays intact
            per_slice = max(devs.size // n_dcn, 1)
            whole = max((_shrink // per_slice) * per_slice, per_slice)
            devs = devs.reshape(-1)[:whole]
            shape[names.index("dcn")] = whole // per_slice
        else:
            devs = devs.reshape(-1)[:_shrink]
    n = devs.size
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    total = int(np.prod(shape))
    if total > n:
        raise ValueError(f"mesh {dict(zip(names, shape))} needs {total} "
                         f"devices, only {n} available")
    mesh = Mesh(devs[:total].reshape(shape), tuple(names))
    return mesh


def dcn_slice_count(mesh: Mesh) -> int:
    """Number of DCN slices in the mesh (1 when there is no dcn axis)."""
    if "dcn" not in mesh.axis_names:
        return 1
    return max(int(mesh.shape["dcn"]), 1)


def slice_size(mesh: Mesh) -> int:
    """Devices per DCN slice (the whole mesh when single-slice)."""
    return mesh.devices.size // dcn_slice_count(mesh)


def set_mesh(mesh: Optional[Mesh]):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _current_mesh


def default_mesh() -> Mesh:
    """Current mesh, or a 1-axis 'dp' mesh over all devices."""
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = create_mesh({"dp": -1})
    return _current_mesh


def mesh_axis_size(name: str, mesh: Optional[Mesh] = None) -> int:
    m = mesh or get_mesh()
    if m is None or name not in m.axis_names:
        return 1
    return m.shape[name]


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    prev = get_mesh()
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


# The COMPILE mesh is a separate channel set only while a compiled
# trainer traces its step: layers use it to place sharding constraints
# on intermediates. It must not be satisfied by a mesh that merely got
# cached through default_mesh() — eager tape ops also trace (jax.vjp)
# and would otherwise pick up constraints from an unrelated mesh.
_compile_mesh: Optional[Mesh] = None


def get_compile_mesh() -> Optional[Mesh]:
    return _compile_mesh


@contextlib.contextmanager
def compile_mesh_guard(mesh: Mesh):
    """Used by SpmdTrainer around compiled-step calls: publishes the
    mesh on BOTH channels (ambient get_mesh for e.g. ring attention
    routing, compile channel for sharding constraints)."""
    global _compile_mesh
    prev_c, _compile_mesh = _compile_mesh, mesh
    with mesh_guard(mesh):
        try:
            yield mesh
        finally:
            _compile_mesh = prev_c
