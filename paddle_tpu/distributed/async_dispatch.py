"""Async dispatch plumbing: lazy step results + host-sync accounting.

The dispatch-bound regime (BENCH_r05: 35% MFU with kernels that should
do better) comes from the HOST side of the step loop: calling
``float(loss)`` after every compiled step serializes dispatch against
device completion, so the host can never run ahead and queue work.  JAX's
async dispatch hides device latency only while nobody reads a value back.

This module is the read-back discipline:

- :class:`StepResult` wraps the device scalar a compiled step returns.
  It *is not* the number — it becomes the number (one blocking host
  transfer) only when somebody calls ``float()`` / formats / compares
  it.  ``hapi.Model.fit`` and ``bench.py`` force results only every
  ``log_freq`` steps, so the steps in between are pure dispatch.
- :class:`LazyValue` defers an arbitrary zero-arg computation (metric
  ``accumulate()``) the same way.
- a process-wide **sync counter**: every forced read-back increments it,
  which is how tests prove "at most one blocking host sync per
  ``log_freq`` window" instead of hand-waving it.

Nothing here imports jax at module scope; wrapped values just need
``__float__`` (device arrays, Tensors, numpy scalars all qualify).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

__all__ = ["StepResult", "LazyValue", "host_sync_count",
           "record_host_sync", "reset_host_sync_count", "resolve"]

_lock = threading.Lock()
_SYNC_COUNT = 0
_SYNC_METRIC = None


def record_host_sync(n: int = 1) -> None:
    """Count a blocking host<-device read-back (or an explicit barrier).
    Mirrored into the unified metrics registry (host_syncs_total) under
    the same lock — the fleet loadgen drives replicas on threads, and
    an unsynchronized ``+=`` on the shared child would lose counts."""
    global _SYNC_COUNT, _SYNC_METRIC
    with _lock:
        _SYNC_COUNT += n
        if _SYNC_METRIC is None:
            from ..observability import metrics as _metrics
            _SYNC_METRIC = _metrics.counter(
                "host_syncs_total", "blocking host<-device read-backs")
        _SYNC_METRIC.inc(n)


def host_sync_count() -> int:
    return _SYNC_COUNT


def reset_host_sync_count() -> int:
    """Zero the counter, returning the old value (test bracketing)."""
    global _SYNC_COUNT
    with _lock:
        old, _SYNC_COUNT = _SYNC_COUNT, 0
    return old


class _Deferred:
    """Shared force-on-read machinery for StepResult/LazyValue."""

    _timings: Optional[dict]
    _resolved: bool
    _value: Any

    def _compute(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def resolve(self):
        """Force the value (blocking host sync on first call; cached)."""
        if not self._resolved:
            t0 = time.perf_counter()
            self._value = self._compute()
            self._resolved = True
            record_host_sync()
            if self._timings is not None:
                self._timings["sync_ms"] = (
                    self._timings.get("sync_ms", 0.0)
                    + (time.perf_counter() - t0) * 1e3)
        return self._value

    # -- number protocol: anything that reads the value forces it -------
    def __float__(self):
        return float(self.resolve())

    def __int__(self):
        return int(self.resolve())

    def __bool__(self):
        return bool(self.resolve())

    def __format__(self, spec):
        v = self.resolve()
        try:
            return format(float(v), spec)
        except (TypeError, ValueError):
            return format(v, spec)

    def __repr__(self):
        if self._resolved:
            return f"{type(self).__name__}({self._value!r})"
        return f"{type(self).__name__}(<pending>)"

    def __str__(self):
        return str(self.resolve())

    def __array__(self, dtype=None):
        import numpy as np
        return np.asarray(self.resolve(), dtype=dtype)

    # NB: no __eq__/__hash__ overrides — identity semantics keep the
    # hash/eq contract intact and stop container membership tests from
    # silently forcing a per-step device sync.  Compare values
    # explicitly via float(result).
    def __lt__(self, other):
        return float(self) < other

    def __le__(self, other):
        return float(self) <= other

    def __gt__(self, other):
        return float(self) > other

    def __ge__(self, other):
        return float(self) >= other

    def __add__(self, other):
        return float(self) + other

    __radd__ = __add__

    def __sub__(self, other):
        return float(self) - other

    def __rsub__(self, other):
        return other - float(self)

    def __mul__(self, other):
        return float(self) * other

    __rmul__ = __mul__

    def __truediv__(self, other):
        return float(self) / other

    def __rtruediv__(self, other):
        return other / float(self)

    def __round__(self, ndigits=None):
        return round(float(self), ndigits)

    def __neg__(self):
        return -float(self)

    def __abs__(self):
        return abs(float(self))


class StepResult(_Deferred):
    """Lazy result of one compiled training/eval step.

    Wraps the on-device loss scalar.  Reading it (``float()``, format,
    comparison, ``numpy()``) blocks until the device produced the value —
    ONE host sync, counted — and caches the float.  Until then the host
    keeps dispatching ahead of the device.

    ``outputs`` carries the step's forward outputs (device arrays) when
    the caller requested them; they are never synced here.
    """

    __slots__ = ("_raw", "_value", "_resolved", "_timings", "outputs")

    def __init__(self, loss, timings: Optional[dict] = None, outputs=None):
        self._raw = loss
        self._value = None
        self._resolved = False
        self._timings = timings
        self.outputs = outputs

    @property
    def loss(self):
        """The underlying device array (no sync)."""
        return self._raw

    @staticmethod
    def _unwrap(v):
        # Tensor -> its array.  Duck-typed `.data` is NOT safe here:
        # numpy values expose .data as a memoryview
        try:
            from ..core.tensor import Tensor
            if isinstance(v, Tensor):
                return v.data
        except Exception:  # pragma: no cover - core always importable
            pass
        return v

    def _compute(self):
        data = self._unwrap(self._raw)
        try:
            return float(data)
        except (TypeError, ValueError):
            import numpy as np
            return float(np.asarray(data))

    def item(self):
        return self.resolve()

    def block_until_ready(self):
        """Barrier: wait for the device to finish this step (counted as a
        sync point; no host transfer)."""
        t0 = time.perf_counter()
        target = self._unwrap(self._raw)
        if hasattr(target, "block_until_ready"):
            target.block_until_ready()
        record_host_sync()
        if self._timings is not None:
            self._timings["sync_ms"] = (
                self._timings.get("sync_ms", 0.0)
                + (time.perf_counter() - t0) * 1e3)
        return self

    def __getattr__(self, name):
        # delegate array-ish attribute access (dtype, shape, astype, ...)
        # to the wrapped device value; never syncs by itself
        return getattr(object.__getattribute__(self, "_raw"), name)


class LazyValue(_Deferred):
    """Defer an arbitrary zero-arg computation (metric accumulate) until
    read; the first read is the (counted) host sync."""

    __slots__ = ("_fn", "_value", "_resolved", "_timings")

    def __init__(self, fn: Callable[[], Any], timings: Optional[dict] = None):
        self._fn = fn
        self._value = None
        self._resolved = False
        self._timings = timings

    def _compute(self):
        return self._fn()


def resolve(value):
    """Force a possibly-deferred value to its concrete form (floats stay
    floats, lists from multi-topk metrics stay lists)."""
    if isinstance(value, _Deferred):
        v = value.resolve()
        try:
            return float(v)
        except (TypeError, ValueError):
            return v
    return value
