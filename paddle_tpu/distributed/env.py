"""Distributed environment: rank/world discovery + JAX runtime init.

Reference: the env-variable contract set by fleet/launch.py
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS,
launch_utils.py:164-258) and dygraph init_parallel_env (parallel.py:57).
TPU-native: `jax.distributed.initialize` (coordinator rendezvous)
replaces the TCP ncclUniqueId exchange (gen_comm_id_helper.cc); inside
one process, "world size" for SPMD purposes is the number of addressable
devices times the process count.
"""
from __future__ import annotations

import os
from typing import Optional

_initialized = False


def get_rank() -> int:
    """Process rank (reference paddle.distributed.get_rank)."""
    for var in ("PADDLE_TRAINER_ID", "RANK", "JAX_PROCESS_INDEX"):
        if var in os.environ:
            return int(os.environ[var])
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    """Number of processes (reference paddle.distributed.get_world_size)."""
    for var in ("PADDLE_TRAINERS_NUM", "WORLD_SIZE", "JAX_PROCESS_COUNT"):
        if var in os.environ:
            return int(os.environ[var])
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1


def get_endpoints():
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    return eps.split(",") if eps else []


def get_current_endpoint() -> Optional[str]:
    return os.environ.get("PADDLE_CURRENT_ENDPOINT")


def init_parallel_env():
    """Multi-host JAX runtime bootstrap (reference parallel.py:57
    init_parallel_env -> NCCLParallelContext::Init). Safe to call on a
    single process (no-op)."""
    global _initialized
    if _initialized:
        return
    world = get_world_size()
    if world > 1 and ("JAX_COORDINATOR_ADDRESS" in os.environ or
                      "PADDLE_MASTER" in os.environ):
        import jax
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or \
            os.environ.get("PADDLE_MASTER")
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=world,
            process_id=get_rank())
    _initialized = True


def is_initialized() -> bool:
    return _initialized


# ---------------------------------------------------------------------------
# heartbeat (failure detection / elastic runtime)
# ---------------------------------------------------------------------------
_last_beat = 0.0


def heartbeat(min_interval: float = 1.0) -> bool:
    """Signal liveness to the launcher's watchdog (reference: the
    elastic manager's worker heartbeat). No-op unless the launcher
    enabled it (PADDLE_HEARTBEAT_DIR env, set by launch
    --heartbeat_timeout); throttled to one file touch per
    `min_interval` seconds so per-step calls cost one time() check.

    Compiled trainers call this every train_step; call it yourself in
    hand-rolled loops that go long between steps."""
    import time as _time
    global _last_beat
    hb_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
    if not hb_dir:
        return False
    now = _time.time()
    if now - _last_beat < min_interval:
        return True
    _last_beat = now
    path = os.path.join(hb_dir, f"hb.{get_rank()}")
    try:
        with open(path, "a"):
            os.utime(path, None)
    except OSError:
        return False
    return True


class ParallelEnv:
    """reference fluid/dygraph/parallel.py:68 ParallelEnv — env-derived
    rank/world_size/device info for dygraph DDP (prefer get_rank() /
    get_world_size())."""

    def __init__(self):
        import os
        self._rank = get_rank()
        self._world_size = get_world_size()
        self._device_id = int(os.environ.get("FLAGS_selected_tpus",
                              os.environ.get("FLAGS_selected_gpus", "0"))
                              .split(",")[0] or 0)

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def nranks(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def dev_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return get_current_endpoint() or ""

    @property
    def trainer_endpoints(self):
        return get_endpoints() or []
