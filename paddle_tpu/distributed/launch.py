"""Multi-process launcher — `python -m paddle_tpu.distributed.launch`.

Reference: python/paddle/distributed/fleet/launch.py:208
(launch_collective), launch_utils.py:164 (Pod), :258 (get_cluster),
:435-491 (start_local_trainers: one subprocess per device with
PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS env + log redirection),
:526 (watch_local_trainers: tear the pod down when any trainer dies).

TPU-native deltas: the rendezvous is JAX's coordinator service
(jax.distributed.initialize inside env.init_parallel_env) instead of a
raw-TCP ncclUniqueId exchange, so the launcher only has to agree on a
coordinator address and export the same PADDLE_* env contract the
reference uses. On a TPU pod slice the runtime usually launches one
process per host out-of-band; this launcher covers single-host
multi-process (CPU rings, tests — the reference's localhost cluster
strategy, test_dist_base.py:668) and explicit multi-host via --ips.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["launch", "get_cluster", "Pod", "TrainerProc", "find_free_port",
           "read_hosts_file", "HOSTS_FILE_ENV"]

# elastic membership: a file the scheduler/operator keeps current with
# the SURVIVING host set (one `ip[:nproc]` per line, '#' comments).
# When set, every (re)launch attempt re-reads it, so a pod that lost a
# host after preemption re-forms over the survivors at a smaller world
# size instead of demanding the original --ips back; the trainers then
# elastic-restore their checkpoints onto the smaller mesh.
HOSTS_FILE_ENV = "PADDLE_ELASTIC_HOSTS_FILE"


def read_hosts_file(path: Optional[str],
                    default_nproc: int) -> Optional[list]:
    """[(ip, nproc)] from an elastic hosts file.  None means 'no
    membership info' (missing/unreadable file -> caller falls back to
    the static --ips contract); an EMPTY list is meaningful — the
    operator truncated the file to say zero hosts survive, and the
    launcher must give up rather than relaunch at the old world size."""
    if not path or not os.path.isfile(path):
        return None
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                ip, _, n = line.partition(":")
                try:
                    nproc = int(n) if n else default_nproc
                except ValueError:
                    nproc = default_nproc
                out.append((ip.strip(), max(1, nproc)))
    except OSError:
        return None
    return out


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class TrainerProc:
    """reference launch_utils.py TrainerProc."""
    rank: int
    proc: subprocess.Popen
    log_path: Optional[str] = None
    log_fh: object = None


@dataclass
class Pod:
    """This host's slice of the cluster (reference launch_utils.py:164)."""
    addr: str
    ranks: List[int] = field(default_factory=list)
    endpoints: List[str] = field(default_factory=list)


def get_cluster(ips: List[str], nproc_per_node: int,
                start_port: Optional[int] = None,
                nproc_map: Optional[dict] = None):
    """All endpoints + this host's Pod (reference get_cluster:258).
    nproc_map ({ip: nproc}) lets an elastic relaunch give survivors
    per-host process counts that differ from the static default."""
    endpoints, pods = [], []
    for ip in ips:
        nproc = (nproc_map or {}).get(ip, nproc_per_node)
        ports = [find_free_port() if (start_port is None and
                                      ip in ("127.0.0.1", "localhost"))
                 else (start_port or 6170) + i
                 for i in range(nproc)]
        pod = Pod(addr=ip)
        for p in ports:
            pod.ranks.append(len(endpoints))
            ep = f"{ip}:{p}"
            pod.endpoints.append(ep)
            endpoints.append(ep)
        pods.append(pod)
    return endpoints, pods


def trainer_env_vars(rank: int, world: int, endpoints: List[str],
                     coordinator: str) -> dict:
    """The per-rank env contract — single source of truth shared with
    spawn.py (reference launch_utils.py:435-466)."""
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        # TPU-native rendezvous (env.init_parallel_env)
        "PADDLE_MASTER": coordinator,
        "JAX_COORDINATOR_ADDRESS": coordinator,
    }


def _trainer_env(rank: int, world: int, endpoints: List[str],
                 coordinator: str) -> dict:
    env = dict(os.environ)
    env.update(trainer_env_vars(rank, world, endpoints, coordinator))
    # children get the async-collective / latency-hiding XLA flags
    # (PADDLE_TPU_OVERLAP): their jax has not initialized yet, so this
    # is the one place the env knob can still take effect on real
    # accelerator backends (no-op on host platforms)
    from .overlap import ensure_xla_overlap_flags
    ensure_xla_overlap_flags(env=env)
    return env


def _local_addrs(probe_ips=()) -> set:
    addrs = {"127.0.0.1", "localhost"}
    try:
        host = socket.gethostname()
        addrs.add(host)
        addrs.add(socket.gethostbyname(host))
    except OSError:  # pragma: no cover
        pass
    # hostname often resolves to 127.0.1.1, not the NIC address in --ips;
    # the UDP-connect trick reveals the interface used to reach each peer
    for ip in probe_ips:
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((ip, 9))
                addrs.add(s.getsockname()[0])
        except OSError:  # pragma: no cover
            pass
    return addrs


def start_local_trainers(pod: Pod, world: int, endpoints: List[str],
                         coordinator: str, training_script: str,
                         script_args: List[str],
                         log_dir: Optional[str] = None
                         ) -> List[TrainerProc]:
    """reference start_local_trainers (launch_utils.py:435)."""
    procs = []
    for rank in pod.ranks:
        env = _trainer_env(rank, world, endpoints, coordinator)
        cmd = [sys.executable, "-u", training_script] + list(script_args)
        log_fh, log_path = None, None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"workerlog.{rank}")
            log_fh = open(log_path, "w")
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=log_fh if log_fh else None,
            stderr=subprocess.STDOUT if log_fh else None)
        procs.append(TrainerProc(rank=rank, proc=proc, log_path=log_path,
                                 log_fh=log_fh))
    return procs


HEARTBEAT_ENV = "PADDLE_HEARTBEAT_DIR"
RC_HEARTBEAT_LOST = 98  # pod exit code for a hung (not crashed) trainer


def heartbeat_path(hb_dir: str, rank: int) -> str:
    return os.path.join(hb_dir, f"hb.{rank}")


def watch_local_trainers(procs: List[TrainerProc],
                         poll_interval: float = 0.5,
                         heartbeat_dir: Optional[str] = None,
                         heartbeat_timeout: float = 0.0) -> int:
    """Tear the pod down when any trainer dies (reference
    watch_local_trainers, launch_utils.py:526) — or, with heartbeats
    enabled, when any trainer goes silent for heartbeat_timeout seconds
    (the failure-detection role of the reference's elastic manager; a
    rank hung in a dead collective never exits on its own).  Returns the
    pod's exit code (first non-zero child, RC_HEARTBEAT_LOST for hangs,
    else 0)."""
    start = time.time()
    try:
        while True:
            alive, rc = 0, 0
            for t in procs:
                code = t.proc.poll()
                if code is None:
                    alive += 1
                elif code != 0:
                    rc = code
            if rc != 0:
                _terminate(procs)
                return rc
            if alive == 0:
                return 0
            if heartbeat_dir and heartbeat_timeout > 0:
                now = time.time()
                for t in procs:
                    if t.proc.poll() is not None:
                        continue
                    p = heartbeat_path(heartbeat_dir, t.rank)
                    try:
                        last = os.path.getmtime(p)
                    except OSError:
                        # no beat yet: measure from launch (startup +
                        # first compile count against the same budget)
                        last = start
                    if now - last > heartbeat_timeout:
                        print(f"launch: rank {t.rank} heartbeat lost "
                              f"({now - last:.0f}s > "
                              f"{heartbeat_timeout:.0f}s); tearing down",
                              file=sys.stderr, flush=True)
                        _terminate(procs)
                        return RC_HEARTBEAT_LOST
            time.sleep(poll_interval)
    except KeyboardInterrupt:  # pragma: no cover
        _terminate(procs)
        raise
    finally:
        for t in procs:
            if t.log_fh:
                t.log_fh.close()


def _terminate(procs: List[TrainerProc], grace: float = 3.0):
    for t in procs:
        if t.proc.poll() is None:
            t.proc.terminate()
    deadline = time.time() + grace
    for t in procs:
        while t.proc.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if t.proc.poll() is None:
            t.proc.kill()


def launch(args=None) -> int:
    parser = argparse.ArgumentParser(
        "paddle_tpu.distributed.launch",
        description="start one training process per rank "
                    "(reference fleet/launch.py)")
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--ips", type=str, default="127.0.0.1",
                        help="comma-separated host ips")
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--start_port", type=int, default=None)
    parser.add_argument("--elastic_retries", type=int, default=0,
                        help="relaunch the whole pod up to N times after "
                             "a crash or lost heartbeat (pair with "
                             "checkpoint auto-resume for fault-tolerant "
                             "training)")
    parser.add_argument("--heartbeat_timeout", type=float, default=0.0,
                        help="seconds of trainer silence before the pod "
                             "is declared hung (0 = disabled); trainers "
                             "beat automatically from train_step")
    parser.add_argument("--elastic_hosts_file", type=str,
                        default=os.environ.get(HOSTS_FILE_ENV),
                        help="membership file re-read before every "
                             "(re)launch attempt: one `ip[:nproc]` per "
                             "line — the SURVIVING host set. With it, a "
                             "preemption drain or crash relaunches over "
                             "whatever hosts remain (smaller world size) "
                             "and the trainers elastic-restore their "
                             "checkpoints onto the new mesh, instead of "
                             "requiring the original --ips world back")
    parser.add_argument("training_script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    a = parser.parse_args(args)

    static_ips = [ip.strip() for ip in a.ips.split(",") if ip.strip()]

    def _resolve_hosts():
        """Current host set: the elastic hosts file when given (re-read
        per attempt — it IS the surviving set), else the static --ips."""
        hosts = read_hosts_file(a.elastic_hosts_file, a.nproc_per_node)
        if hosts is None:
            return static_ips, None
        return [ip for ip, _ in hosts], {ip: n for ip, n in hosts}

    # preemption handling: SIGTERM on the launcher forwards to every
    # trainer so their PreemptionGuards drain the in-flight step and
    # checkpoint; the pod then exits with the trainers' status instead
    # of elastic-restarting into a doomed relaunch
    current_procs: List[TrainerProc] = []
    preempted = [False]

    def _forward_sigterm(signum, frame):
        preempted[0] = True
        print("launch: SIGTERM received; forwarding to trainers for "
              "drain + checkpoint", file=sys.stderr, flush=True)
        for t in current_procs:
            if t.proc.poll() is None:
                t.proc.terminate()

    try:
        prev_term = signal.signal(signal.SIGTERM, _forward_sigterm)
    except ValueError:  # pragma: no cover (non-main thread)
        prev_term = None

    attempts = a.elastic_retries + 1
    for attempt in range(attempts):
        # fresh ports each attempt: the dead pod's sockets may linger;
        # fresh membership each attempt: survivors only (elastic shrink)
        ips, nproc_map = _resolve_hosts()
        if not ips:
            print("launch: elastic hosts file lists no survivors; "
                  "giving up", file=sys.stderr, flush=True)
            return 1
        endpoints, pods = get_cluster(ips, a.nproc_per_node,
                                      a.start_port, nproc_map)
        # pick THIS host's pod (reference matches the node ip); each host
        # of a multi-host cluster runs its own launcher over the same
        # --ips
        if len(pods) == 1:
            pod = pods[0]
        else:
            local = _local_addrs(probe_ips=ips)
            mine = [p for p in pods if p.addr in local]
            if not mine:
                raise SystemExit(
                    f"none of --ips {ips} matches this host "
                    f"({sorted(local)}); include this host's ip")
            pod = mine[0]
        coordinator = f"{ips[0]}:{find_free_port()}" if ips[0] in (
            "127.0.0.1", "localhost") else endpoints[0]

        hb_dir = None
        if a.heartbeat_timeout > 0:
            hb_dir = a.log_dir or os.path.join(
                os.environ.get("TMPDIR", "/tmp"),
                f"paddle_hb_{os.getpid()}_{attempt}")
            os.makedirs(hb_dir, exist_ok=True)
            # stale beats from a previous attempt/run would trip the
            # watchdog instantly — each attempt starts with a clean slate
            for f in os.listdir(hb_dir):
                if f.startswith("hb."):
                    try:
                        os.remove(os.path.join(hb_dir, f))
                    except OSError:
                        pass
            os.environ[HEARTBEAT_ENV] = hb_dir  # inherited by children

        procs = start_local_trainers(pod, len(endpoints), endpoints,
                                     coordinator, a.training_script,
                                     a.script_args, a.log_dir)
        current_procs[:] = procs
        rc = watch_local_trainers(procs,
                                  heartbeat_dir=hb_dir,
                                  heartbeat_timeout=a.heartbeat_timeout)
        if preempted[0] and a.elastic_hosts_file and \
                attempt + 1 < attempts:
            # SIGTERM drain finished (trainers checkpointed + exited):
            # instead of dying at the original world size, re-form the
            # mesh from whatever the hosts file NOW lists — the
            # surviving set — and let auto-resume elastic-restore the
            # checkpoints onto the smaller (or regrown) topology
            preempted[0] = False
            print("launch: preemption drain complete; re-forming from "
                  "the surviving host set", file=sys.stderr, flush=True)
            time.sleep(0.5)
            continue
        if rc == 0 or preempted[0]:
            # clean finish, or a preemption drain (trainers that
            # checkpointed and exited 0 make the whole pod exit 0)
            if prev_term is not None:
                signal.signal(signal.SIGTERM, prev_term)
            return rc
        if attempt + 1 < attempts:
            print(f"launch: pod failed (rc={rc}); elastic restart "
                  f"{attempt + 2}/{attempts}", file=sys.stderr,
                  flush=True)
            time.sleep(1.0)
    if prev_term is not None:
        signal.signal(signal.SIGTERM, prev_term)
    return rc


if __name__ == "__main__":
    sys.exit(launch())
