"""Fault-tolerant training primitives: async verified checkpoints +
preemption handling.

Production training stacks (Check-N-Run's decoupled, verified
checkpointing; Orbax-style async snapshots) treat failure as the common
case. This module gives paddle_tpu the same posture on top of
distributed/checkpoint.py:

- CheckpointManager: the training thread pays only the device->host
  snapshot; serialization + checksum + atomic commit run on a
  background thread. Keep-last-K GC, and resume that walks candidates
  newest-first, skipping anything that fails manifest/checksum
  validation — a truncated newest checkpoint falls back to the previous
  valid one instead of killing the run.
- PreemptionGuard: converts SIGTERM/SIGINT into a flag the training
  loop polls, so the in-flight step drains, a final synchronous
  checkpoint commits, and the process exits cleanly for the next launch
  (elastic restart / auto-resume) to pick up.

Reference analogue: fluid/incubate/checkpoint/auto_checkpoint.py kept
epoch-granular snapshots keyed by env; here the unit is the compiled
trainer's full state and the integrity story is explicit.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Optional

from .checkpoint import (checkpoint_candidates, gc_stale_tmps,
                         latest_checkpoint, read_checkpoint,
                         restore_trainer, snapshot_trainer,
                         write_checkpoint)

__all__ = ["CheckpointManager", "PreemptionGuard"]


class CheckpointManager:
    """Async, integrity-checked, keep-last-K trainer checkpoints.

    save(trainer, step) snapshots device state to host on the calling
    thread (the only part that must synchronize with training) and
    commits the manifest directory `ckpt-{step}` on a background
    thread. Saves are serialized: a new save first joins the previous
    one, and any background failure is re-raised there — an I/O error
    can delay training but never silently drop checkpoints.

    restore_latest(trainer) restores the newest checkpoint that passes
    validation, falling back across corrupt/truncated candidates.
    """

    def __init__(self, directory: str, keep_last: int = 3,
                 async_save: bool = True, prefix: str = "ckpt-",
                 on_error=None):
        if any(directory.startswith(s) for s in ("hdfs://", "afs://")):
            raise NotImplementedError(
                "CheckpointManager manages local directories; for "
                "hdfs:// use save_trainer (single file) — its fs layer "
                "already retries with backoff")
        self.directory = directory
        self.keep_last = max(1, int(keep_last))
        self.async_save = bool(async_save)
        self.prefix = prefix
        # on_error(exc): invoked (on the thread that next calls save()/
        # wait()) instead of re-raising a background commit failure —
        # for trainers that prefer to log-and-continue.  Without it the
        # failure RAISES at the next save()/wait(), so a dead ckpt dir
        # can never silently discard every snapshot.
        self.on_error = on_error
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._stuck = False   # a wait(timeout) expired on this thread
        self._saves = 0
        self._fallbacks = 0
        self._commit_failures = 0
        self._reshard_restores = 0
        self._reform_waits = 0
        self.last_restore_info: Optional[dict] = None
        self.last_snapshot_ms: Optional[float] = None
        self.last_commit_ms: Optional[float] = None

    # -- write path --------------------------------------------------------
    def _path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"{self.prefix}{int(step)}")

    def save(self, trainer, step: Optional[int] = None,
             extra: Optional[dict] = None, block: bool = False) -> str:
        """Checkpoint `trainer` as `{prefix}{step}` (default: the
        trainer's own step count). Returns the final path immediately;
        with async_save the commit happens in the background — call
        wait() (or the next save) to join it."""
        self.wait()  # serialize saves + surface any background failure
        # (wait() refuses fast if a previous commit was declared stuck)
        # a save racing an in-flight mesh reform would snapshot half-
        # rebound sharding trees: queue behind the reform instead (a
        # periodic saver thread vs the training thread mid-reform)
        self._await_reform(trainer)
        if step is None:
            step = getattr(trainer, "_step_count", 0)
        path = self._path_for(step)
        t0 = time.perf_counter()
        state = snapshot_trainer(trainer, extra=extra)
        self.last_snapshot_ms = (time.perf_counter() - t0) * 1e3

        def commit():
            t1 = time.perf_counter()
            write_checkpoint(state, path)
            self._gc()
            self.last_commit_ms = (time.perf_counter() - t1) * 1e3

        self._saves += 1
        if self.async_save and not block:
            def run():
                try:
                    commit()
                except BaseException as e:  # surfaced by wait()
                    self._commit_failures += 1
                    self._error = e
            self._thread = threading.Thread(
                target=run, name="ckpt-writer", daemon=True)
            self._thread.start()
        else:
            try:
                commit()
            except BaseException:
                self._commit_failures += 1
                raise
        return path

    def _await_reform(self, trainer, timeout: Optional[float] = None):
        """Block while `trainer.reform_in_progress` is set — an
        in-memory mesh reform owns the trainer state, so a save queues
        behind it.  Bounded: a reform stuck past the timeout
        (PADDLE_TPU_REFORM_WAIT_S, default 120s) raises instead of
        wedging the saver forever."""
        if not getattr(trainer, "reform_in_progress", False):
            return
        if timeout is None:
            timeout = float(os.environ.get("PADDLE_TPU_REFORM_WAIT_S",
                                           "120"))
        self._reform_waits += 1
        t0 = time.monotonic()
        while getattr(trainer, "reform_in_progress", False):
            if time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"mesh reform still in progress after {timeout}s; "
                    f"refusing to snapshot mid-reform")
            time.sleep(0.01)

    def wait(self, timeout: Optional[float] = None):
        """Join the in-flight background save; surface its failure —
        re-raised here, or routed to the on_error callback when one was
        given.  With `timeout` (seconds) a commit stuck on dead storage
        raises TimeoutError instead of hanging the trainer forever (the
        commit thread is left running; a later wait() can still join
        it)."""
        t = self._thread
        if t is not None:
            if timeout is None and self._stuck and t.is_alive():
                # a previous wait(timeout) already declared this commit
                # stuck on dead storage; an untimed join here (from
                # save()/latest()/restore_latest()) would reintroduce
                # the exact hang the timeout exists to prevent — refuse
                # fast, the caller decides what to do
                raise TimeoutError(
                    f"previous checkpoint commit is still stuck "
                    f"(directory {self.directory!r}); refusing an "
                    f"untimed join behind dead storage")
            t.join(timeout)
            if t.is_alive():
                self._stuck = True
                raise TimeoutError(
                    f"checkpoint commit still running after "
                    f"{timeout}s (directory {self.directory!r}; slow or "
                    f"dead storage?)")
            self._stuck = False
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            if self.on_error is not None:
                self.on_error(err)
            else:
                raise err

    def _candidates(self):
        """(step, path) pairs, newest first, committed finals only."""
        return checkpoint_candidates(self.directory, self.prefix)

    def _gc(self):
        """Keep the newest keep_last checkpoints; drop older ones and
        any stale .tmp staging orphans from crashed saves."""
        import shutil
        for _, path in self._candidates()[self.keep_last:]:
            try:
                shutil.rmtree(path) if os.path.isdir(path) \
                    else os.remove(path)
            except OSError:
                pass
        gc_stale_tmps(self.directory, self.prefix)

    # -- read path ---------------------------------------------------------
    def latest(self, validate: bool = True) -> Optional[str]:
        """Path of the newest valid checkpoint (no restore)."""
        self.wait()
        return latest_checkpoint(self.directory, prefix=self.prefix,
                                 validate=validate, gc_tmp=False)

    def restore_latest(self, trainer,
                       elastic: Optional[bool] = None) -> Optional[dict]:
        """Restore the newest checkpoint that validates AND unpickles,
        falling back to older ones past corruption. Returns the saved
        'extra' dict, or None when no usable checkpoint exists.

        Elastic: when the candidate records a different mesh than the
        trainer's (v2 states), the restore auto-RESHARDS onto the live
        topology — a preempted dp=8 job resumes as dp=4 from the same
        directory.  `elastic=False` (or resume_elastic=False on the
        trainer) makes a cross-topology candidate an error instead; it
        is NOT skipped as a fallback, because silently rewinding to an
        older step over a topology policy would lose work.

        A structural mismatch against the live trainer (wrong model)
        still raises — that is a configuration error, not bitrot."""
        self.wait()
        for _, path in self._candidates():
            try:
                # read_checkpoint validates the manifest itself — one
                # read + one sha256 pass per candidate, not two
                state = read_checkpoint(path)
            except Exception as e:
                self._fallbacks += 1
                print(f"resilience: skipping corrupt checkpoint {path} "
                      f"({type(e).__name__}: {e}); falling back",
                      file=sys.stderr, flush=True)
                continue
            extra = restore_trainer(trainer, state, elastic=elastic)
            info = getattr(trainer, "_last_restore_info", None)
            self.last_restore_info = info
            if info and info.get("resharded"):
                self._reshard_restores += 1
                print(f"resilience: resharded {path} from mesh "
                      f"{info['saved_mesh_axes']} onto "
                      f"{info['mesh_axes']}", file=sys.stderr,
                      flush=True)
            return extra
        return None

    @property
    def stats(self) -> dict:
        return {
            "saves": self._saves,
            "fallbacks": self._fallbacks,
            "commit_failures": self._commit_failures,
            "reshard_restores": self._reshard_restores,
            "reform_waits": self._reform_waits,
            "async": self.async_save,
            "keep_last": self.keep_last,
            "last_snapshot_ms": self.last_snapshot_ms,
            "last_commit_ms": self.last_commit_ms,
        }


class PreemptionGuard:
    """Convert SIGTERM/SIGINT into a poll-able flag so training loops
    drain the in-flight step and checkpoint before exiting.

    Usage:
        guard = PreemptionGuard().install()
        try:
            for batch in loader:
                trainer.train_step(*batch)
                if guard.preempted:
                    manager.save(trainer, block=True)
                    break
        finally:
            guard.uninstall()

    A second signal while draining falls through to the previous
    handler (default: terminate) so a stuck drain can still be killed.
    Installation is a no-op off the main thread (Python restricts
    signal.signal to it) — preempted just stays False there.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self.signum: Optional[int] = None

    def _handler(self, signum, frame):
        if self._event.is_set():
            # second delivery: restore + re-raise so the default action
            # (or the launcher's handler) runs — no infinite drain
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        self.signum = signum
        self._event.set()
        # black box BEFORE the drain (observability.flightrec): the
        # preemption bundle must exist even if the drain/checkpoint
        # that follows wedges or the grace period expires.  Handlers
        # run on the main thread between bytecodes; the dump is small
        # host-side JSON.  Never raises.
        try:
            from ..observability import flightrec
            flightrec.note_event("preemption", signum=int(signum))
            flightrec.dump("sigterm")
        except Exception:   # pragma: no cover - dump path broken
            pass

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self  # pragma: no cover (signals need the main thread)
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev = {}

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
