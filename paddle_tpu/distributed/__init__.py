"""paddle.distributed parity, TPU-native.

Reference: python/paddle/distributed/ (§2.5 of SURVEY.md). The NCCL
ring_id world becomes a jax.sharding.Mesh whose named axes ARE the
parallel dimensions (dp/tp/pp/sp/ep); collectives are XLA ops inside
compiled programs, exposed eagerly through this package's API for
dygraph-style parity.
"""
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env,
    is_initialized)
from .mesh import (  # noqa: F401
    Mesh, get_mesh, set_mesh, create_mesh, mesh_axis_size,
    dcn_slice_count, slice_size)
from . import membership  # noqa: F401
from .membership import (  # noqa: F401
    SliceMembership, DcnCollectiveGuard, SliceLostError)
from .collective import (  # noqa: F401
    all_reduce, all_gather, reduce, broadcast, scatter, barrier,
    all_to_all, send, recv, split, ReduceOp, new_group)
from .parallel import DataParallel  # noqa: F401
from . import parallel_layers  # noqa: F401
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from . import fleet  # noqa: F401
from . import spmd  # noqa: F401
from .spmd import SpmdTrainer, dp_train_step, StepResult  # noqa: F401
from . import async_dispatch  # noqa: F401
from .async_dispatch import (  # noqa: F401
    LazyValue, host_sync_count, reset_host_sync_count)
from .recompute import recompute, RecomputeWrapper  # noqa: F401
from . import moe  # noqa: F401
from .moe import (  # noqa: F401
    MoELayer, ExpertParallelFFN, collect_aux_losses, add_aux_loss)
from . import ring_attention as ring_attention_mod  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention, ring_attention_local, sequence_parallel_attention)
from . import checkpoint  # noqa: F401
from .checkpoint import (  # noqa: F401
    save_trainer, load_trainer, latest_checkpoint)
from . import resilience  # noqa: F401
from .resilience import CheckpointManager, PreemptionGuard  # noqa: F401
from . import launch as launch_mod  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import overlap  # noqa: F401
from .overlap import overlap_enabled, ensure_xla_overlap_flags  # noqa: F401
