"""DataParallel wrapper + sharded train-step builder.

Reference: python/paddle/fluid/dygraph/parallel.py:321 (DataParallel →
C++ Reducer bucketed allreduce, imperative/reducer.cc) and the compiled
equivalent CompiledProgram.with_data_parallel.

TPU-native: there is no Reducer — `dp_train_step` builds a jit'd step
whose gradients carry a psum over the 'dp' mesh axis; XLA buckets and
overlaps the allreduce with the backward automatically (the exact
optimization Reducer::MarkVarReady hand-codes). The eager DataParallel
wrapper exists for API parity: in a single-process world forward is
unchanged, and `apply_collective_grads` is the explicit-sync escape
hatch (no-op at world 1).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from . import env
from .collective import all_reduce, ReduceOp
from .mesh import Mesh, NamedSharding, PartitionSpec, default_mesh

__all__ = ["DataParallel", "scale_loss", "dp_shard_batch", "param_shardings"]


def scale_loss(loss):
    """reference parallel.py scale_loss (divide by nranks before
    backward so the summed allreduce averages)."""
    n = env.get_world_size()
    if n <= 1:
        return loss
    return loss / n


class DataParallel(Layer):
    """paddle.DataParallel parity (reference fluid/dygraph/parallel.py:321).

    find_unused_parameters / comm_buffer_size are accepted for API parity;
    XLA's fused backward makes both moot (no per-bucket scheduling)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return scale_loss(loss)

    def apply_collective_grads(self):
        """Allreduce all parameter grads (reference Reducer's job)."""
        if env.get_world_size() <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, op=ReduceOp.SUM, group=self.group)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    # attribute passthrough for wrapped-layer access parity
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


def param_shardings(params, mesh: Mesh):
    """NamedShardings for a pytree of Parameters/arrays: use
    param.pspec when a parallel layer marked one, replicate otherwise."""
    def one(p):
        spec = getattr(p, "pspec", None) or PartitionSpec()
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(
        one, params, is_leaf=lambda x: isinstance(x, Tensor))


def dp_shard_batch(batch, mesh: Optional[Mesh] = None, axis="dp"):
    """Place a host batch sharded over the dp axis (the reference fed
    per-device scopes; here one device_put with a NamedSharding)."""
    m = mesh or default_mesh()
    def put(x):
        arr = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        spec = PartitionSpec(axis, *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(m, spec))
    return jax.tree_util.tree_map(
        put, batch, is_leaf=lambda x: isinstance(x, Tensor))
