"""DataParallel wrapper + sharded train-step builder.

Reference: python/paddle/fluid/dygraph/parallel.py:321 (DataParallel →
C++ Reducer bucketed allreduce, imperative/reducer.cc) and the compiled
equivalent CompiledProgram.with_data_parallel.

TPU-native: there is no Reducer — `dp_train_step` builds a jit'd step
whose gradients carry a psum over the 'dp' mesh axis; XLA buckets and
overlaps the allreduce with the backward automatically (the exact
optimization Reducer::MarkVarReady hand-codes). The eager DataParallel
wrapper exists for API parity: in a single-process world forward is
unchanged, and `apply_collective_grads` is the explicit-sync escape
hatch (no-op at world 1).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from . import env
from .collective import all_gather, all_reduce, ReduceOp
from .mesh import Mesh, NamedSharding, PartitionSpec, default_mesh

__all__ = ["DataParallel", "scale_loss", "dp_shard_batch", "param_shardings"]


def scale_loss(loss):
    """reference parallel.py scale_loss (divide by nranks before
    backward so the summed allreduce averages)."""
    n = env.get_world_size()
    if n <= 1:
        return loss
    return loss / n


class DataParallel(Layer):
    """paddle.DataParallel parity (reference fluid/dygraph/parallel.py:321).

    apply_collective_grads fuses dense grads into comm_buffer_size-MB
    buckets — ONE allreduce per bucket, the reference Reducer's bucket
    fusion (imperative/reducer.h:48) — and allgathers row-sparse
    (SelectedRows) grads as (rows, values) pairs like the reference's
    sparse-var allgather branch.  find_unused_parameters is accepted for
    API parity (XLA zero-fills unused grads)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return scale_loss(loss)

    def apply_collective_grads(self):
        """Bucketed allreduce of all parameter grads (the Reducer's job:
        reference imperative/reducer.cc groups grads into comm buffers
        and launches one fused allreduce per bucket)."""
        if env.get_world_size() <= 1:
            return
        from ..core.selected_rows import SelectedRows

        dense, sparse = [], []
        for p in self._layers.parameters():
            if p.grad is None:
                continue
            if isinstance(p.grad, SelectedRows):
                sparse.append(p)
            else:
                dense.append(p)

        # sparse grads: allgather (rows, values) across ranks — summing
        # a SelectedRows is concatenation (merge() dedupes lazily)
        for p in sparse:
            g = p.grad
            rows = all_gather(g.rows, group=self.group)
            vals = all_gather(g.values, group=self.group)
            rows = rows.data if isinstance(rows, Tensor) else rows
            vals = vals.data if isinstance(vals, Tensor) else vals
            p.grad = SelectedRows(rows.reshape(-1),
                                  vals.reshape(-1, *g.values.shape[1:]),
                                  g.full_shape)

        # dense grads: fuse into ~comm_buffer_size MB flat buckets
        import math

        def flush(bucket):
            if not bucket:
                return
            flat = jnp.concatenate(
                [b.grad.data.reshape(-1).astype(jnp.float32)
                 for b in bucket])
            red = all_reduce(Tensor(flat), op=ReduceOp.SUM,
                             group=self.group)
            off = 0
            for b in bucket:
                n = max(math.prod(b.grad.data.shape), 1)
                b.grad._data = red.data[off:off + n].reshape(
                    b.grad.data.shape).astype(b.grad.data.dtype)
                off += n

        budget = max(int(self.comm_buffer_size * 1024 * 1024), 1)
        bucket, used = [], 0
        for p in dense:
            nbytes = max(math.prod(p.grad.data.shape), 1) * 4
            if bucket and used + nbytes > budget:
                flush(bucket)
                bucket, used = [], 0
            bucket.append(p)
            used += nbytes
        flush(bucket)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    # attribute passthrough for wrapped-layer access parity
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


def param_shardings(params, mesh: Mesh):
    """NamedShardings for a pytree of Parameters/arrays: use
    param.pspec when a parallel layer marked one, replicate otherwise."""
    def one(p):
        spec = getattr(p, "pspec", None) or PartitionSpec()
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(
        one, params, is_leaf=lambda x: isinstance(x, Tensor))


def dp_shard_batch(batch, mesh: Optional[Mesh] = None, axis="dp"):
    """Place a host batch sharded over the dp axis (the reference fed
    per-device scopes; here one device_put with a NamedSharding)."""
    m = mesh or default_mesh()
    def put(x):
        arr = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        spec = PartitionSpec(axis, *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(m, spec))
    return jax.tree_util.tree_map(
        put, batch, is_leaf=lambda x: isinstance(x, Tensor))
