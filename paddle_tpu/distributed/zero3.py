"""ZeRO-3 with overlapped parameter all-gather (Rajbhandari et al. 2020).

The GSPMD ZeRO-3 path (`spmd.zero_sharding_spec` with stage>=3) leaves
the gather placement to XLA: params live dp-sharded and the partitioner
inserts an all-gather at each use site.  That is correct but gives the
scheduler no structure to hide the gathers behind — on jaxlib 0.4.x the
partitioned module typically gathers a layer's weights right before its
matmuls need them, serializing ICI transfer and MXU work.

This module expresses the schedule explicitly, the way the scan-over-
layers stack makes possible: inside `shard_map` over the dp axis, the
layer scan's carry holds the CURRENT layer's already-gathered weights
while the body issues the all-gather for layer i+1 — two independent op
islands XLA's async collectives can overlap (the `PADDLE_TPU_OVERLAP`
flags in `distributed.overlap` turn the latency-hiding scheduler on for
real backends).  Because the gather is differentiated explicitly, its
transpose is `psum_scatter`: gradients leave the backward REDUCE-
SCATTERED over dp instead of all-reduced, which is the other half of
ZeRO-3 — per-device grad (and param) memory drops ~1/dp and the wire
moves 2x less gradient data.

Numerics are untouched: the gather reconstructs the exact replicated
weights, every per-token op inside the block is batch-local, and
reduce-scatter + sharded-Adam-update is elementwise-equal to
all-reduce + full-Adam-update on the same shard.  The parity tests and
the multichip dryrun assert this against the synchronous stage-3 path.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from . import mesh as _mesh
from .mesh import Mesh, PartitionSpec, shard_map

__all__ = ["zero3_shard_dims", "zero3_scan_available",
           "scan_layers_zero3"]


def zero3_shard_dims(stacked: Dict[str, jax.Array], axis: str,
                     dp_size: int) -> Dict[str, Optional[int]]:
    """Per-param shard dim (on the UNSTACKED [per-layer] shape, so dim 0
    here is the layer axis and is never sharded).  Must agree with the
    placement `spmd.zero_sharding_spec` gives the live params, so the
    shard_map in_specs match the arrays' residency and no resharding
    copy is inserted."""
    from .spmd import zero_sharding_spec
    dims = {}
    for name, arr in stacked.items():
        spec = zero_sharding_spec(tuple(arr.shape[1:]), PartitionSpec(),
                                  axis, dp_size)
        d = next((i for i, a in enumerate(tuple(spec)) if a == axis),
                 None)
        dims[name] = None if d is None else d + 1   # +1: layer axis
    return dims


def zero3_scan_available(mesh: Optional[Mesh], axis: str,
                         batch: int) -> bool:
    """The overlapped path needs a real dp axis and a batch it can
    shard; anything else falls back to the GSPMD formulation (same
    memory story, XLA-placed gathers)."""
    return (mesh is not None and axis in mesh.axis_names
            and mesh.shape[axis] > 1 and batch % mesh.shape[axis] == 0)


def scan_layers_zero3(call_block: Callable, stacked: Dict[str, jax.Array],
                      h: jax.Array, mesh: Mesh, axis: str,
                      use_remat: bool = False, policy=None) -> jax.Array:
    """Run the stacked layer scan with one-layer-ahead gathered params.

    call_block(layer_params: {name: full array}, h) -> h runs ONE block
    with fully-gathered weights; `stacked` maps name -> [L, ...] arrays
    (dp-sharded per `zero3_shard_dims`); `h` is the [B, ...] activation,
    batch-sharded over `axis`.
    """
    dp = mesh.shape[axis]
    shard_dims = zero3_shard_dims(stacked, axis, dp)
    nd = {n: a.ndim for n, a in stacked.items()}
    param_specs = {}
    for n, d in shard_dims.items():
        dims = [None] * nd[n]
        if d is not None:
            dims[d] = axis
        param_specs[n] = PartitionSpec(*dims)
    batch_spec = PartitionSpec(axis)

    def local(h_loc, shards):
        def gather_layer(xs):
            """One layer's param shards -> full arrays (dim offsets are
            post-layer-slice, hence shard_dims[n] - 1)."""
            return {n: (x if shard_dims[n] is None else
                        _mesh.all_gather(x, axis, axis=shard_dims[n] - 1))
                    for n, x in xs.items()}

        if use_remat:
            # remat path: the gather lives INSIDE the checkpointed
            # region, so the per-iteration residual is the 1/dp SHARD
            # and the backward re-gathers — classic ZeRO-3.  The
            # prefetch-carry formulation below would make the gathered
            # full params a per-layer residual (L x full model on every
            # device), i.e. MORE memory than the sync stage-3 path the
            # overlap replaces.  Trade: no one-layer-ahead prefetch
            # here; the forward gather is still a separate op island
            # the async scheduler can hoist within the body.
            def body(hc, xs_cur):
                return call_block(gather_layer(xs_cur), hc), None

            body = jax.checkpoint(body, policy=policy, prevent_cse=False)
            h_out, _ = jax.lax.scan(body, h_loc, shards)
            return h_out

        # Non-remat residual note: the scan transpose keeps each
        # iteration's gathered weights alive for the backward — but the
        # synchronous GSPMD stage-3 scan does the same (its in-body
        # gather result is equally a per-iteration residual), so this is
        # parity, not a regression.  The '~1/dp param+grad memory' claim
        # is about PERSISTENT state (params, grads, optimizer); for 1/dp
        # backward residuals too, enable recompute — the remat branch
        # above re-gathers from shards.
        def body(carry, xs_next):
            hc, cur = carry
            # issue layer i+1's gather FIRST: it has no data dependence
            # on layer i's compute, so the async scheduler can run the
            # transfer under the block's matmuls
            nxt = gather_layer(xs_next)
            out = call_block(cur, hc)
            return (out, nxt), None

        first = gather_layer({n: s[0] for n, s in shards.items()})
        # iteration i consumes layer i+1's shard, read by dynamic index
        # from the closed-over shard stacks — NOT a jnp.roll copy, which
        # would transiently double the per-device sharded-param memory
        # (the final iteration re-gathers layer 0 into a dead carry
        # slot, keeping the scan body uniform)
        n_layers = next(iter(shards.values())).shape[0]

        def body_i(carry, i):
            nxt_shard = {
                n: jax.lax.dynamic_index_in_dim(
                    s, jax.lax.rem(i + 1, n_layers), 0, keepdims=False)
                for n, s in shards.items()}
            return body(carry, nxt_shard)

        (h_out, _), _ = jax.lax.scan(body_i, (h_loc, first),
                                     jnp.arange(n_layers))
        return h_out

    smapped = shard_map(local, mesh=mesh,
                        in_specs=(batch_spec, param_specs),
                        out_specs=batch_spec, check_vma=False)
    return smapped(h, stacked)
