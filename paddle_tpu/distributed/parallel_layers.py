"""Tensor-model-parallel layers.

Reference: python/paddle/distributed/collective.py:492-566
(_parallel_linear / _parallel_embedding behind paddle.distributed.split):
column-parallel Linear (shard out_features; allgather output),
row-parallel Linear (shard in_features; allreduce output), vocab-sharded
Embedding (shard_index + allreduce).

TPU-native: the layers hold FULL logical weights annotated with a
PartitionSpec over the 'tp'/'mp' mesh axis (weight.pspec); under
pjit/shard_map GSPMD places the shards and inserts the
allreduce/allgather exactly where the reference's explicit c_allreduce /
c_allgather ops sat. Inside shard_map (manual mode) the forward uses the
explicit lax collectives. Eagerly (world=1) they behave like plain
layers, which matches the reference's nranks==1 fast path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from .mesh import PartitionSpec, get_mesh, mesh_axis_size

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "mark_sharding"]


def mark_sharding(param, spec: PartitionSpec):
    """Attach a PartitionSpec to a Parameter; compiled trainers read
    param.pspec to build NamedShardings (the reference marks tensors
    is_distributed for the same purpose, collective.py:520)."""
    param.pspec = spec
    param.is_distributed = any(s is not None for s in spec)
    return param


def _in_shard_map(axis_name) -> bool:
    """True when tracing inside shard_map with axis_name bound."""
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _quantized_linear(x, weight, bias, mode: str):
    """x @ W (+ b) through ops.fake_quant_matmul: quantized forward
    (int8/fp8 per-channel amax scaling), straight-through backward —
    the AQT training path.  The bias rides full precision."""
    from ..ops.quantized_matmul import fake_quant_matmul

    def fn(a, w, *b):
        y = fake_quant_matmul(a, w, mode)
        return y + b[0] if b else y

    if bias is None:
        return apply(fn, x, weight, name="quantized_linear")
    return apply(fn, x, weight, bias, name="quantized_linear")


class ColumnParallelLinear(Layer):
    """Y = X @ W with W sharded on columns (out_features). Output is
    either gathered (gather_output=True, reference default in split) or
    left sharded for a following RowParallelLinear.  ``quantize=
    'int8'/'fp8'`` swaps the matmul for the fake-quant AQT path
    (quantized forward, straight-through backward); None keeps the
    exact unquantized lowering."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, bias_attr=None, gather_output=True,
                 axis_name="tp", quantize=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.axis_name = axis_name
        self.quantize = quantize
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, PartitionSpec(None, axis_name))
        self.bias = None
        if has_bias and bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)
            mark_sharding(self.bias, PartitionSpec(axis_name))

    def forward(self, x):
        if self.quantize:
            y = _quantized_linear(x, self.weight, self.bias, self.quantize)
        else:
            y = F.linear(x, self.weight, self.bias)
        if self.gather_output and _in_shard_map(self.axis_name):
            name = self.axis_name
            from . import mesh as _mesh
            y = apply(lambda a: _mesh.all_gather(a, name, axis=a.ndim - 1),
                      y, name="c_allgather")
        return y


class RowParallelLinear(Layer):
    """Y = X @ W with W sharded on rows (in_features); partial products
    are summed with allreduce (reference _parallel_linear axis=0 path →
    c_allreduce_sum on output)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, bias_attr=None, input_is_parallel=True,
                 axis_name="tp", quantize=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.axis_name = axis_name
        self.quantize = quantize
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, PartitionSpec(axis_name, None))
        self.bias = None
        if has_bias and bias_attr is not False:
            # bias added AFTER the reduce, replicated
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)
            mark_sharding(self.bias, PartitionSpec(None))

    def forward(self, x):
        if self.quantize:
            y = _quantized_linear(x, self.weight, None, self.quantize)
        else:
            y = F.linear(x, self.weight, None)
        if _in_shard_map(self.axis_name):
            name = self.axis_name
            y = apply(lambda a: jax.lax.psum(a, name), y,
                      name="c_allreduce_sum")
        if self.bias is not None:
            y = y + self.bias
        return y


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dimension sharded (reference
    _parallel_embedding, collective.py:527: shard_index + lookup +
    allreduce)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 axis_name="mp", name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.axis_name = axis_name
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        mark_sharding(self.weight, PartitionSpec(axis_name, None))

    def forward(self, x):
        if not _in_shard_map(self.axis_name):
            return F.embedding(x, self.weight)
        name = self.axis_name

        def fn(ids, w):
            # local shard covers rows [rank*per, (rank+1)*per)
            per = w.shape[0]
            rank = jax.lax.axis_index(name)
            start = rank * per
            local = ids.astype(jnp.int32) - start
            in_range = (local >= 0) & (local < per)
            safe = jnp.clip(local, 0, per - 1)
            out = jnp.take(w, safe, axis=0)
            out = jnp.where(in_range[..., None], out, 0.0)
            return jax.lax.psum(out, name)

        return apply(fn, x, self.weight, name="vocab_parallel_embedding")
