"""Activation recompute (checkpointing).

Reference: fluid/backward.py:725 `_append_backward_ops_with_checkpoints_`
(re-runs forward segments inside the backward program) and
fleet/meta_optimizers/recompute_optimizer.py. TPU-native: `jax.checkpoint`
(remat) — XLA drops the segment's activations and re-executes its forward
in the backward pass, trading FLOPs for HBM exactly like the reference's
program rewrite, but scheduled by the compiler.

Works in BOTH execution modes:
- eagerly, `recompute(block, x)` records ONE tape node whose vjp is the
  checkpointed function's vjp (recompute happens inside `backward()`);
- under a compiled trainer trace, the remat region is inlined into the
  jaxpr and honored by jax.grad.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax

from ..core.autograd import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["recompute", "RecomputeWrapper", "checkpoint_policy"]

_POLICIES = {
    "full": None,  # save nothing, recompute everything
    "dots": "checkpoint_dots",
    "dots_no_batch": "checkpoint_dots_with_no_batch_dims",
    "nothing": "nothing_saveable",
    "everything": "everything_saveable",
}


def checkpoint_policy(name: Optional[str]):
    """Map strategy.recompute_configs['policy'] names onto
    jax.checkpoint_policies."""
    if name is None or name == "full":
        return None
    attr = _POLICIES.get(name, name)
    pol = getattr(jax.checkpoint_policies, attr, None)
    if pol is None:
        raise ValueError(f"unknown recompute policy {name!r}")
    return pol


def recompute(function, *args, policy=None, **kwargs):
    """paddle.distributed.fleet.utils.recompute parity: run `function`
    (a Layer or a Tensor-level callable) without saving its internal
    activations; they are recomputed during backward.
    """
    if isinstance(function, Layer):
        param_objs = [p for _, p in function.named_parameters()]
    else:
        param_objs = []
    n_params = len(param_objs)

    def pure(*flat):
        p_arrs, in_arrs = flat[:n_params], flat[n_params:]
        originals = [p._data for p in param_objs]
        for p, a in zip(param_objs, p_arrs):
            p._data = a
        try:
            wrapped = [Tensor(a) if not isinstance(a, Tensor) else a
                       for a in in_arrs]
            out = function(*wrapped, **kwargs)
        finally:
            for p, a in zip(param_objs, originals):
                p._data = a
        return jax.tree_util.tree_map(
            lambda x: x.data if isinstance(x, Tensor) else x, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    ckpt = jax.checkpoint(pure, policy=checkpoint_policy(policy))
    return apply(ckpt, *param_objs, *args, name="recompute")


class RecomputeWrapper(Layer):
    """Wrap a block so every forward goes through `recompute` (the layer
    form of the reference's checkpoint list). `enable(False)` turns it
    into a transparent passthrough."""

    def __init__(self, layer: Layer, policy: Optional[str] = None):
        super().__init__()
        self._inner = layer
        self._policy = policy
        self._active = True

    def enable(self, active: bool = True):
        self._active = active
        return self

    def forward(self, *args, **kwargs):
        if not self._active:
            return self._inner(*args, **kwargs)
        return recompute(self._inner, *args, policy=self._policy, **kwargs)
