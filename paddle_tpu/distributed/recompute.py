"""Activation recompute (checkpointing).

Reference: fluid/backward.py:725 `_append_backward_ops_with_checkpoints_`
(re-runs forward segments inside the backward program) and
fleet/meta_optimizers/recompute_optimizer.py. TPU-native: `jax.checkpoint`
(remat) — XLA drops the segment's activations and re-executes its forward
in the backward pass, trading FLOPs for HBM exactly like the reference's
program rewrite, but scheduled by the compiler.

Works in BOTH execution modes:
- eagerly, `recompute(block, x)` records ONE tape node whose vjp is the
  checkpointed function's vjp (recompute happens inside `backward()`);
- under a compiled trainer trace, the remat region is inlined into the
  jaxpr and honored by jax.grad.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax

from ..core.autograd import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["recompute", "RecomputeWrapper", "checkpoint_policy"]

_POLICIES = {
    "full": None,  # save nothing, recompute everything
    "dots": "checkpoint_dots",
    "dots_no_batch": "checkpoint_dots_with_no_batch_dims",
    "nothing": "nothing_saveable",
    "everything": "everything_saveable",
}


def checkpoint_policy(name: Optional[str]):
    """Map strategy.recompute_configs['policy'] names onto
    jax.checkpoint_policies."""
    if name is None or name == "full":
        return None
    attr = _POLICIES.get(name, name)
    pol = getattr(jax.checkpoint_policies, attr, None)
    if pol is None:
        raise ValueError(f"unknown recompute policy {name!r}")
    return pol


def recompute(function, *args, policy=None, **kwargs):
    """paddle.distributed.fleet.utils.recompute parity: run `function`
    (a Layer or a Tensor-level callable) without saving its internal
    activations; they are recomputed during backward.

    Buffers the block mutates in place (BatchNorm running stats) are
    threaded through the checkpointed region as explicit inputs/outputs —
    the block's buffer tensors are restored after tracing and re-assigned
    with the region's OUTPUT values, so no inner-trace tracer ever leaks
    into live module state.
    """
    from .moe import add_aux_loss, collect_aux_losses

    if isinstance(function, Layer):
        param_objs = [p for _, p in function.named_parameters()]
        buf_objs = [b for _, b in function.named_buffers()
                    if b is not None]
    else:
        param_objs, buf_objs = [], []
    n_params, n_bufs = len(param_objs), len(buf_objs)
    meta = {}

    def pure(*flat):
        p_arrs = flat[:n_params]
        b_arrs = flat[n_params:n_params + n_bufs]
        in_arrs = flat[n_params + n_bufs:]
        orig_p = [p._data for p in param_objs]
        orig_b = [b._data for b in buf_objs]
        for o, a in zip(param_objs, p_arrs):
            o._data = a
        for o, a in zip(buf_objs, b_arrs):
            o._data = a
        try:
            wrapped = [Tensor(a) if not isinstance(a, Tensor) else a
                       for a in in_arrs]
            # aux losses (MoE routers) produced inside the remat region
            # are tracers of the INNER checkpoint trace; they must leave
            # the region as explicit outputs, then be re-emitted outside
            # (otherwise adding them to the loss later leaks the tracer)
            with collect_aux_losses() as aux:
                out = function(*wrapped, **kwargs)
            aux_arrs = tuple(a.data if isinstance(a, Tensor) else a
                             for a in aux)
            new_bufs = tuple(b._data for b in buf_objs)
        finally:
            for o, a in zip(param_objs, orig_p):
                o._data = a
            for o, a in zip(buf_objs, orig_b):
                o._data = a
        out_arrs = jax.tree_util.tree_map(
            lambda x: x.data if isinstance(x, Tensor) else x, out,
            is_leaf=lambda x: isinstance(x, Tensor))
        leaves, treedef = jax.tree_util.tree_flatten(out_arrs)
        meta["treedef"] = treedef
        meta["n_out"] = len(leaves)
        return tuple(leaves) + new_bufs + aux_arrs

    ckpt = jax.checkpoint(pure, policy=checkpoint_policy(policy))
    res = apply(ckpt, *param_objs, *buf_objs, *args, name="recompute")
    res = res if isinstance(res, tuple) else (res,)
    out_leaves = list(res[:meta["n_out"]])
    for b, nv in zip(buf_objs, res[meta["n_out"]:meta["n_out"] + n_bufs]):
        b._data = nv.data
    for a in res[meta["n_out"] + n_bufs:]:
        add_aux_loss(a)
    out = jax.tree_util.tree_unflatten(meta["treedef"], out_leaves)
    return out


class RecomputeWrapper(Layer):
    """Wrap a block so every forward goes through `recompute` (the layer
    form of the reference's checkpoint list). `enable(False)` turns it
    into a transparent passthrough."""

    def __init__(self, layer: Layer, policy: Optional[str] = None):
        super().__init__()
        self._inner = layer
        self._policy = policy
        self._active = True

    def enable(self, active: bool = True):
        self._active = active
        return self

    def forward(self, *args, **kwargs):
        if not self._active:
            return self._inner(*args, **kwargs)
        return recompute(self._inner, *args, policy=self._policy, **kwargs)
