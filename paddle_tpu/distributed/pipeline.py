"""Pipeline parallelism — GPipe as ONE compiled SPMD program.

Reference mapping: the reference implements pipelining with a C++
scheduler (SectionWorker::TrainFiles, /root/reference/paddle/fluid/
framework/section_worker.cc:34-110: per-microbatch scopes, run all
Forward ops, then all Backward, then Optimize) driven by a program split
that inserts send_v2/recv_v2 at stage boundaries
(fluid/optimizer.py:3718 PipelineOptimizer,
fleet/meta_optimizers/pipeline_optimizer.py:136-286).

TPU-native re-design: no scheduler process at all. The whole schedule is
a `lax.scan` over pipeline ticks inside one jitted step under
`shard_map`:

- the N identical stage blocks' parameters are STACKED on a leading
  layer axis and sharded over the 'pp' mesh axis (each pp rank holds a
  contiguous slab of layers) — the analogue of the reference's
  per-device program sections;
- at every tick each rank runs its slab (an inner `lax.scan` over its
  layers, optionally remat'ed) and hands its activation to the next rank
  with `lax.ppermute` — the send_v2/recv_v2 pair, but compiled into the
  program so XLA overlaps compute with the ICI transfer;
- rank 0 injects a fresh microbatch each tick, the last rank banks its
  finished microbatch; after M + S - 1 ticks all M microbatches are done
  (GPipe F-then-B: jax.grad transposes the scan, which replays the
  ticks in reverse — exactly the reference's all-Forward-then-all-
  Backward order, with send/recv transposed automatically);
- embedding ("pre") and head ("post") parameters are replicated across
  'pp'; their gradients are psum'd over the mesh.

Data parallelism composes: with a ('dp', 'pp') mesh the microbatch dim
is additionally sharded over 'dp' and gradients are psum'd over 'dp'
inside the same program.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..func import functional_call
from ..nn.layer_base import Layer
from .fleet.strategy import DistributedStrategy
from .mesh import Mesh, NamedSharding, PartitionSpec, shard_map

__all__ = ["GPipeTrainer", "stack_block_params"]


def stack_block_params(blocks: Sequence[Layer]) -> Dict[str, jax.Array]:
    """Stack the (structurally identical) blocks' params on a leading
    layer axis: {name: [L, ...]}. The per-stage slab is this array
    sharded over 'pp' on dim 0."""
    per_block = [dict(b.named_parameters()) for b in blocks]
    keys = list(per_block[0].keys())
    for d in per_block[1:]:
        if list(d.keys()) != keys:
            raise ValueError(
                "pipeline stages must be structurally identical layers "
                f"(param sets differ: {keys} vs {list(d.keys())})")
    return {k: jnp.stack([d[k].data for d in per_block]) for k in keys}


def _call(layer: Layer, params, *args, training=True, buffers=None):
    out, _ = functional_call(layer, params, buffers or {}, *args,
                             training=training)
    return out


def stack_block_buffers(blocks: Sequence[Layer]) -> Dict[str, jax.Array]:
    """Stack the blocks' buffers on a leading layer axis (the buffer
    analogue of stack_block_params)."""
    per_block = [{n: b.data for n, b in blk.named_buffers()
                  if b is not None} for blk in blocks]
    keys = list(per_block[0].keys())
    for d in per_block[1:]:
        if list(d.keys()) != keys:
            raise ValueError("pipeline blocks' buffer sets differ")
    return {k: jnp.stack([d[k] for d in per_block]) for k in keys}


class GPipeTrainer:
    """Compiled GPipe trainer over a mesh with a 'pp' axis (and optional
    'dp' axis).

    Parameters
    ----------
    pre, blocks, post : Layers — `pre(inputs) -> h`, N identical
        `block(h) -> h`, `post(h) -> outputs`. N must divide by the pp
        degree. Stages must be buffer-free (like the reference's
        SectionWorker, which forbids cross-microbatch state).
    optimizer : functional form used inside the step.
    loss_fn : callable(outputs, labels) -> scalar.
    num_microbatches : GPipe M (reference pipeline_configs
        'accumulate_steps').
    """

    def __init__(self, pre: Layer, blocks: Sequence[Layer], post: Layer,
                 optimizer, loss_fn: Callable, mesh: Mesh,
                 num_microbatches: int = 2, pp_axis: str = "pp",
                 dp_axis: str = "dp", remat: bool = True,
                 strategy: Optional[DistributedStrategy] = None,
                 dedupe_head: bool = True, buffer_mode: str = "forbid"):
        if pp_axis not in mesh.axis_names:
            raise ValueError(f"mesh has no '{pp_axis}' axis")
        if buffer_mode not in ("forbid", "frozen"):
            raise ValueError(
                f"buffer_mode must be 'forbid' or 'frozen', got "
                f"{buffer_mode!r}")
        self.buffer_mode = buffer_mode
        has_buffers = any(
            b is not None
            for l in (pre, post, blocks[0])
            for _, b in l.named_buffers())
        if has_buffers and buffer_mode == "forbid":
            raise NotImplementedError(
                "pipeline stage has buffers; buffer-UPDATING layers "
                "(train-mode BatchNorm running stats) cannot pipeline "
                "(reference SectionWorker has the same restriction). "
                "Pass buffer_mode='frozen' to run them with read-only "
                "buffers: forward math is unchanged (train-mode BN "
                "normalizes with batch stats), but running statistics "
                "are NOT tracked — calibrate eval stats separately.")
        # MoE routers emit aux losses; blocks and post thread them through
        # the schedule, but the pre stage runs inside the tick scan where
        # they would be dropped silently — fail loudly instead
        from .moe import MoELayer
        if any(isinstance(sl, MoELayer) for sl in pre.sublayers(True)):
            raise NotImplementedError(
                "MoE layers in the pipeline 'pre' stage are not supported "
                "(their router aux losses cannot leave the injection scan)")
        self.pre, self.post = pre, post
        self.template = blocks[0]
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.pp_axis, self.dp_axis = pp_axis, dp_axis
        self.pp_size = mesh.shape[pp_axis]
        self.dp_size = mesh.shape.get(dp_axis, 1) \
            if dp_axis in mesh.axis_names else 1
        self.num_micro = num_microbatches
        self.remat = remat
        # shard the head+loss over pp ranks (each rank takes M/S of the
        # microbatches) instead of every rank computing all M masked —
        # needs M % S == 0, else the masked fallback runs
        self.dedupe_head = (dedupe_head and
                            num_microbatches % mesh.shape[pp_axis] == 0)
        self.num_layers = len(blocks)
        if self.num_layers % self.pp_size:
            raise ValueError(
                f"{self.num_layers} blocks not divisible by pp degree "
                f"{self.pp_size}")
        self._step_count = 0

        repl = NamedSharding(mesh, PartitionSpec())
        blk_shard = NamedSharding(mesh, PartitionSpec(pp_axis))
        self._specs = {
            "pre": {n: PartitionSpec() for n, _ in pre.named_parameters()},
            "blocks": {k: PartitionSpec(pp_axis)
                       for k in dict(blocks[0].named_parameters())},
            "post": {n: PartitionSpec()
                     for n, _ in post.named_parameters()},
        }
        self.params = {
            "pre": {n: jax.device_put(p.data, repl)
                    for n, p in pre.named_parameters()},
            "blocks": {k: jax.device_put(v, blk_shard)
                       for k, v in stack_block_params(blocks).items()},
            "post": {n: jax.device_put(p.data, repl)
                     for n, p in post.named_parameters()},
        }
        self._param_shardings = {
            "pre": {n: repl for n in self.params["pre"]},
            "blocks": {n: blk_shard for n in self.params["blocks"]},
            "post": {n: repl for n in self.params["post"]},
        }
        # read-only buffers (buffer_mode='frozen'): pre/post replicated,
        # block buffers stacked [L, ...] and captured whole (each rank
        # slices its slab by axis_index inside the shard_map program)
        self._frozen_buffers = None
        if self.buffer_mode == "frozen":
            self._frozen_buffers = {
                "pre": {n: jax.device_put(b.data, repl)
                        for n, b in pre.named_buffers() if b is not None},
                "blocks": {k: jax.device_put(v, repl)
                           for k, v in stack_block_buffers(blocks)
                           .items()},
                "post": {n: jax.device_put(b.data, repl)
                         for n, b in post.named_buffers()
                         if b is not None},
            }
        with jax.transfer_guard("allow"):
            opt_state = optimizer.init_state(self.params)

        # opt state inherits the sharding of its param (same shapes)
        def _st_shard(tree, shards):
            return {k: jax.tree_util.tree_map(
                lambda a, s=shards[k]: jax.device_put(a, s), sub)
                for k, sub in tree.items()}
        self.opt_state = {
            bundle: _st_shard(opt_state[bundle],
                              self._param_shardings[bundle])
            for bundle in opt_state}
        # opt-state sharding tree mirrors opt_state (checkpoint restore)
        self._opt_shardings = {
            bundle: {k: jax.tree_util.tree_map(
                lambda a, s=self._param_shardings[bundle][k]: s, sub)
                for k, sub in opt_state[bundle].items()}
            for bundle in opt_state}
        self._blocks_ref = list(blocks)
        self._compiled = None

    # ------------------------------------------------------------------
    def _stage_fn(self, slab, h, training, buf_slab=None):
        """Run this rank's slab of layers: inner scan over [L/S, ...].
        Returns (h, aux): aux losses (MoE routers) produced inside the
        layer scan leave it as explicit scan outputs."""
        from .moe import collect_aux_losses

        def body(carry, xs):
            layer_params, layer_buf = xs if buf_slab is not None \
                else (xs, None)
            with collect_aux_losses() as aux:
                out = _call(self.template, layer_params, carry,
                            training=training, buffers=layer_buf)
            asum = jnp.float32(0.0)
            for a in aux:
                asum = asum + (a.data if isinstance(a, Tensor)
                               else a).astype(jnp.float32)
            return out, asum

        if self.remat:
            body = jax.checkpoint(body)
        xs = (slab, buf_slab) if buf_slab is not None else slab
        h, auxs = jax.lax.scan(body, h, xs)
        return h, jnp.sum(auxs)

    def _pipeline_forward(self, params, micro_in, micro_lab, training):
        """Per-rank program (inside shard_map). micro_in: [M, mb, ...]."""
        S, M = self.pp_size, self.num_micro
        idx = jax.lax.axis_index(self.pp_axis)
        pre_p, slab, post_p = (params["pre"], params["blocks"],
                               params["post"])
        fb = self._frozen_buffers
        if fb is not None:
            lps = self.num_layers // S
            buf_slab = {k: jax.lax.dynamic_slice_in_dim(v, idx * lps,
                                                        lps, 0)
                        for k, v in fb["blocks"].items()} or None
            pre_buf, post_buf = fb["pre"], fb["post"]
        else:
            buf_slab = pre_buf = post_buf = None

        def pre_fn(i):
            x = jax.lax.dynamic_index_in_dim(micro_in, i, 0,
                                             keepdims=False)
            return _call(self.pre, pre_p, Tensor(x), training=training,
                         buffers=pre_buf)

        # embed ALL microbatches once, outside the tick loop: the old
        # per-tick pre call ran the embedding M+S-1 times on every rank
        pre_emb = jnp.stack([pre_fn(m) for m in range(M)])  # [M, mb, h]

        h0_aval = pre_emb.shape[1:]
        zero = jnp.zeros(h0_aval, pre_emb.dtype)
        out_buf = jnp.zeros((M,) + h0_aval, pre_emb.dtype)

        def tick(carry, t):
            act, out_buf, aux_acc = carry
            y, aux_t = self._stage_fn(slab, act, training, buf_slab)
            # this rank's tick t holds microbatch (t - idx); bubble ticks
            # run on zeros and their router aux must not count
            valid = (t >= idx) & (t < idx + M)
            aux_acc = aux_acc + jnp.where(valid, aux_t, 0.0)
            out_idx = t - (S - 1)
            write = (idx == S - 1) & (out_idx >= 0)
            slot = jnp.clip(out_idx, 0, M - 1)
            prev = jax.lax.dynamic_index_in_dim(out_buf, slot, 0,
                                                keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(write, y, prev), slot, 0)
            if S > 1:
                y_next = jax.lax.ppermute(
                    y, self.pp_axis, [(i, i + 1) for i in range(S - 1)])
            else:
                y_next = y
            inj = jax.lax.dynamic_index_in_dim(
                pre_emb, jnp.clip(t + 1, 0, M - 1), 0, keepdims=False)
            act = jnp.where(idx == 0, inj, y_next)
            return (act, out_buf, aux_acc), None

        # t counts processed ticks: act entering tick t is stage input
        # for microbatch (t - stage); total M + S - 1 ticks
        init_act = jnp.where(idx == 0, pre_emb[0], zero)
        (act, out_buf, aux_acc), _ = jax.lax.scan(
            tick, (init_act, out_buf, jnp.float32(0.0)),
            jnp.arange(M + S - 1))

        from .moe import collect_aux_losses

        def head_loss(h, lab_idx):
            """post + loss for one microbatch activation h."""
            out = _call(self.post, post_p, Tensor(h), training=training,
                        buffers=post_buf)
            out_t = jax.tree_util.tree_map(
                lambda a: Tensor(a, stop_gradient=True), out)
            lab = jax.tree_util.tree_map(
                lambda a: Tensor(jax.lax.dynamic_index_in_dim(
                    a, lab_idx, 0, keepdims=False)), micro_lab)
            lab = lab if isinstance(lab, (list, tuple)) else (lab,)
            l = self.loss_fn(out_t, *lab)
            return (l.data if isinstance(l, Tensor) else l) \
                .astype(jnp.float32)

        if self.dedupe_head and S > 1:
            # head+loss SHARDED over pp: broadcast the finished
            # activations from the last rank (masked psum = one
            # all-reduce), each rank computes M/S of the heads — per-rank
            # head FLOPs drop S-fold vs the masked-everywhere fallback
            Ms = M // S
            bcast = jax.lax.psum(
                jnp.where(idx == S - 1, out_buf,
                          jnp.zeros_like(out_buf)), self.pp_axis)
            mine = jax.lax.dynamic_slice_in_dim(bcast, idx * Ms, Ms, 0)
            acts = [(mine[j], idx * Ms + j) for j in range(Ms)]
            mask_last = False
        else:
            # fallback: every rank runs all M heads, masked to last rank
            acts = [(out_buf[m], m) for m in range(M)]
            mask_last = True
        with collect_aux_losses() as post_aux:
            losses = [head_loss(h, i) for h, i in acts]
        local = jnp.stack(losses).sum() / M
        for a in post_aux:
            arr = (a.data if isinstance(a, Tensor) else a)
            local = local + arr.astype(jnp.float32) / M
        if mask_last:
            local = jnp.where(idx == S - 1, local, 0.0)
        # block aux: each rank saw every microbatch once -> mean over M
        return (local + aux_acc / M) / self.dp_size

    def _build(self, training=True):
        mesh = self.mesh
        P = PartitionSpec
        pp, dp = self.pp_axis, self.dp_axis
        has_dp = self.dp_size > 1

        in_specs_params = {
            "pre": self._specs["pre"], "blocks": self._specs["blocks"],
            "post": self._specs["post"]}
        batch_spec = P(None, dp) if has_dp else P()

        def local_step(params, micro_in, micro_lab):
            def lfn(ps):
                return self._pipeline_forward(ps, micro_in, micro_lab,
                                              training)
            loss, grads = jax.value_and_grad(lfn)(params)
            # replicated pre/post: contributions live on specific pp
            # ranks — sum them; slab grads are rank-local over pp
            axes_repl = (pp, dp) if has_dp else (pp,)
            grads = {
                "pre": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axes_repl), grads["pre"]),
                "blocks": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, dp) if has_dp else g,
                    grads["blocks"]),
                "post": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axes_repl), grads["post"]),
            }
            loss = jax.lax.psum(loss, axes_repl)
            return loss, grads

        grad_specs = dict(in_specs_params)
        smapped = shard_map(
            local_step, mesh=mesh,
            in_specs=(in_specs_params, batch_spec, batch_spec),
            out_specs=(P(), grad_specs),
            check_vma=False)

        def step(params, opt_state, lr, step_no, micro_in, micro_lab):
            loss, grads = smapped(params, micro_in, micro_lab)
            new_params, new_opt = self.optimizer.apply_gradients(
                params, grads, opt_state, lr=lr, step=step_no)
            return new_params, new_opt, loss

        return jax.jit(
            step,
            out_shardings=(self._param_shardings, None, None),
            donate_argnums=(0, 1))

    # ------------------------------------------------------------------
    def _microbatch(self, arr):
        """[B, ...] -> [M, B/M, ...] host-side split + device_put sharded
        over dp on the microbatch dim."""
        a = arr.data if isinstance(arr, Tensor) else jnp.asarray(arr)
        b = a.shape[0]
        if b % self.num_micro:
            raise ValueError(f"batch {b} not divisible by "
                             f"{self.num_micro} microbatches")
        mb = a.reshape((self.num_micro, b // self.num_micro) + a.shape[1:])
        spec = PartitionSpec(
            None, self.dp_axis if (self.dp_size > 1 and
                                   mb.shape[1] % self.dp_size == 0)
            else None, *([None] * (mb.ndim - 2)))
        return jax.device_put(mb, NamedSharding(self.mesh, spec))

    def train_step(self, inputs, labels):
        micro_in = self._microbatch(inputs)
        micro_lab = jax.tree_util.tree_map(
            self._microbatch, labels,
            is_leaf=lambda x: isinstance(x, Tensor))
        if self._compiled is None:
            self._compiled = self._build(training=True)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._step_count + 1, jnp.int32)
        self.params, self.opt_state, loss = self._compiled(
            self.params, self.opt_state, lr, step_no, micro_in, micro_lab)
        self._step_count += 1
        self.optimizer._step_count = self._step_count
        return loss

    # ------------------------------------------------------------------
    def save(self, path: str, extra=None) -> str:
        """Checkpoint params + opt state + step (see SpmdTrainer.save)."""
        from .checkpoint import save_trainer
        return save_trainer(self, path, extra=extra)

    def load(self, path: str) -> dict:
        from .checkpoint import load_trainer
        return load_trainer(self, path)

    # ------------------------------------------------------------------
    def sync_to_model(self):
        """Write trained arrays back into the source layers (unstacking
        the block slabs)."""
        for n, p in self.pre.named_parameters():
            p._data = self.params["pre"][n]
        for n, p in self.post.named_parameters():
            p._data = self.params["post"][n]
        for k, stacked in self.params["blocks"].items():
            host = np.asarray(stacked)
            for i, blk in enumerate(self._blocks_ref):
                dict(blk.named_parameters())[k]._data = jnp.asarray(host[i])
        return self
