"""Pipeline parallelism — GPipe and 1F1B as ONE compiled SPMD program.

Reference mapping: the reference implements pipelining with a C++
scheduler (SectionWorker::TrainFiles, /root/reference/paddle/fluid/
framework/section_worker.cc:34-110: per-microbatch scopes, run all
Forward ops, then all Backward, then Optimize) driven by a program split
that inserts send_v2/recv_v2 at stage boundaries
(fluid/optimizer.py:3718 PipelineOptimizer,
fleet/meta_optimizers/pipeline_optimizer.py:136-286).

TPU-native re-design: no scheduler process at all. The whole schedule is
a `lax.scan` over pipeline ticks inside one jitted step under
`shard_map`:

- the N identical stage blocks' parameters are STACKED on a leading
  layer axis and sharded over the 'pp' mesh axis (each pp rank holds a
  contiguous slab of layers) — the analogue of the reference's
  per-device program sections;
- at every tick each rank runs its slab (an inner `lax.scan` over its
  layers, optionally remat'ed) and hands its activation to the next rank
  with `ppermute` — the send_v2/recv_v2 pair, but compiled into the
  program so XLA overlaps compute with the ICI transfer;
- embedding ("pre") and head ("post") parameters are replicated across
  'pp'; their gradients are psum'd over the mesh.

Two schedules share that machinery (``schedule=`` ctor arg):

- ``"gpipe"``: rank 0 injects a fresh microbatch each tick, the last
  rank banks its finished microbatch; after M + S - 1 ticks all M are
  done, and `jax.grad` transposes the scan — all-Forward-then-all-
  Backward, with activations for every in-flight microbatch live at
  once (peak activation memory O(M));
- ``"1f1b"``: the one-forward-one-backward steady state of Megatron-LM
  (Narayanan et al. 2021, non-interleaved PipeDream-flush).  Each tick
  runs one forward AND one explicitly-written backward: the backward
  wavefront trails the forward by the warmup depth (pp - 1
  microbatches), so a microbatch's gradients start flowing as soon as
  the last stage finishes it instead of after the full fill.  Each rank
  stashes only the stage INPUTS of its in-flight microbatches — at most
  ``min(2*pp - 1, M)`` slots, O(pp) not O(M) — and re-computes the
  stage forward inside `jax.vjp` at the backward tick (activation
  recompute, the standard 1F1B memory/compute trade).  Forward
  activations and backward grad-activations cross stage boundaries with
  two ppermutes per tick whose transfers are independent of the
  adjacent microbatch's compute, exactly the islands the async
  collective scheduler (PADDLE_TPU_OVERLAP) hides.

Data parallelism composes: with a ('dp', 'pp') mesh the microbatch dim
is additionally sharded over 'dp' and gradients are psum'd over 'dp'
inside the same program.
"""
from __future__ import annotations

import functools
import itertools
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..func import functional_call
from ..nn.layer_base import Layer
from . import mesh as _mesh
from .fleet.strategy import DistributedStrategy
from .mesh import Mesh, NamedSharding, PartitionSpec, shard_map

__all__ = ["GPipeTrainer", "stack_block_params"]

# observatory component ids, one per trainer instance (ISSUE 15)
_GPIPE_IDS = itertools.count()


def stack_block_params(blocks: Sequence[Layer]) -> Dict[str, jax.Array]:
    """Stack the (structurally identical) blocks' params on a leading
    layer axis: {name: [L, ...]}. The per-stage slab is this array
    sharded over 'pp' on dim 0."""
    per_block = [dict(b.named_parameters()) for b in blocks]
    keys = list(per_block[0].keys())
    for d in per_block[1:]:
        if list(d.keys()) != keys:
            raise ValueError(
                "pipeline stages must be structurally identical layers "
                f"(param sets differ: {keys} vs {list(d.keys())})")
    return {k: jnp.stack([d[k].data for d in per_block]) for k in keys}


def _call(layer: Layer, params, *args, training=True, buffers=None):
    out, _ = functional_call(layer, params, buffers or {}, *args,
                             training=training)
    return out


def stack_block_buffers(blocks: Sequence[Layer]) -> Dict[str, jax.Array]:
    """Stack the blocks' buffers on a leading layer axis (the buffer
    analogue of stack_block_params)."""
    per_block = [{n: b.data for n, b in blk.named_buffers()
                  if b is not None} for blk in blocks]
    keys = list(per_block[0].keys())
    for d in per_block[1:]:
        if list(d.keys()) != keys:
            raise ValueError("pipeline blocks' buffer sets differ")
    return {k: jnp.stack([d[k] for d in per_block]) for k in keys}


class GPipeTrainer:
    """Compiled GPipe trainer over a mesh with a 'pp' axis (and optional
    'dp' axis).

    Parameters
    ----------
    pre, blocks, post : Layers — `pre(inputs) -> h`, N identical
        `block(h) -> h`, `post(h) -> outputs`. N must divide by the pp
        degree. Stages must be buffer-free (like the reference's
        SectionWorker, which forbids cross-microbatch state).
    optimizer : functional form used inside the step.
    loss_fn : callable(outputs, labels) -> scalar.
    num_microbatches : GPipe M (reference pipeline_configs
        'accumulate_steps').
    """

    def __init__(self, pre: Layer, blocks: Sequence[Layer], post: Layer,
                 optimizer, loss_fn: Callable, mesh: Mesh,
                 num_microbatches: int = 2, pp_axis: str = "pp",
                 dp_axis: str = "dp", remat: bool = True,
                 strategy: Optional[DistributedStrategy] = None,
                 dedupe_head: bool = True, buffer_mode: str = "forbid",
                 schedule: Optional[str] = None,
                 comm_stats: Optional[bool] = None,
                 resume_elastic: Optional[bool] = None):
        if pp_axis not in mesh.axis_names:
            raise ValueError(f"mesh has no '{pp_axis}' axis")
        # elastic resume: the stacked [L, ...] block slabs are saved as
        # GLOBAL arrays, so a pp=4 checkpoint re-splits onto pp=2 (two
        # stage param groups merge per rank) by plain resharding.
        # False = strict same-topology restores only.
        if resume_elastic is None:
            resume_elastic = os.environ.get(
                "PADDLE_TPU_RESUME_ELASTIC", "1") != "0"
        self.resume_elastic = bool(resume_elastic)
        self._reshard_restores = 0
        self._last_restore_info: Optional[dict] = None
        from .overlap import pipeline_schedule_default
        self.schedule = schedule or pipeline_schedule_default()
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got "
                f"{self.schedule!r}")
        if buffer_mode not in ("forbid", "frozen"):
            raise ValueError(
                f"buffer_mode must be 'forbid' or 'frozen', got "
                f"{buffer_mode!r}")
        self.buffer_mode = buffer_mode
        has_buffers = any(
            b is not None
            for l in (pre, post, blocks[0])
            for _, b in l.named_buffers())
        if has_buffers and buffer_mode == "forbid":
            raise NotImplementedError(
                "pipeline stage has buffers; buffer-UPDATING layers "
                "(train-mode BatchNorm running stats) cannot pipeline "
                "(reference SectionWorker has the same restriction). "
                "Pass buffer_mode='frozen' to run them with read-only "
                "buffers: forward math is unchanged (train-mode BN "
                "normalizes with batch stats), but running statistics "
                "are NOT tracked — calibrate eval stats separately.")
        # MoE routers emit aux losses; blocks and post thread them through
        # the schedule, but the pre stage runs inside the tick scan where
        # they would be dropped silently — fail loudly instead
        from .moe import MoELayer
        if any(isinstance(sl, MoELayer) for sl in pre.sublayers(True)):
            raise NotImplementedError(
                "MoE layers in the pipeline 'pre' stage are not supported "
                "(their router aux losses cannot leave the injection scan)")
        self.pre, self.post = pre, post
        self.template = blocks[0]
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.pp_axis, self.dp_axis = pp_axis, dp_axis
        self.pp_size = mesh.shape[pp_axis]
        self.dp_size = mesh.shape.get(dp_axis, 1) \
            if dp_axis in mesh.axis_names else 1
        self.num_micro = num_microbatches
        self.remat = remat
        # shard the head+loss over pp ranks (each rank takes M/S of the
        # microbatches) instead of every rank computing all M masked —
        # needs M % S == 0, else the masked fallback runs
        self.dedupe_head = (dedupe_head and
                            num_microbatches % mesh.shape[pp_axis] == 0)
        self.num_layers = len(blocks)
        # step-time + collective breakdown (mirrors SpmdTrainer.stats);
        # comm analysis AOT-compiles the step a second time → opt-in
        self._comm_enabled = bool(
            comm_stats if comm_stats is not None
            else os.environ.get("PADDLE_TPU_COMM_STATS") == "1")
        self._comm: Optional[dict] = None
        self._timings = {"dispatch_ms": 0.0, "compile_ms_cold": 0.0,
                         "steps_timed": 0}
        # unified telemetry (observability/): same registry + wall timer
        # as SpmdTrainer, labeled trainer="gpipe"
        from ..observability import capture as _capture
        from ..observability import metrics as _obs_metrics
        from ..profiler import StepTimer
        self.step_timer = StepTimer(warmup=1)
        self.step_timer.start()
        self._profile = _capture.ProfileWindow.from_env(kind="train")
        self._m_steps = _obs_metrics.counter(
            "train_steps_total", "completed train steps",
            labels=("trainer",)).labels(trainer="gpipe")
        self._m_step_ms = _obs_metrics.gauge(
            "train_step_time_ms", "last per-step wall time (host)",
            labels=("trainer",)).labels(trainer="gpipe")
        self._m_step_hist = _obs_metrics.histogram(
            "train_step_ms", "per-step wall time",
            labels=("trainer",)).labels(trainer="gpipe")
        # flight recorder + stall watchdog (observability): crash hooks
        # once per process; watchdog thread only when
        # PADDLE_TPU_WATCHDOG_S arms it (checked on the first step)
        from ..observability import flightrec as _flightrec
        _flightrec.install()
        self.watchdog = None
        self._wd_checked = False
        if self.num_layers % self.pp_size:
            raise ValueError(
                f"{self.num_layers} blocks not divisible by pp degree "
                f"{self.pp_size}")
        self._step_count = 0

        repl = NamedSharding(mesh, PartitionSpec())
        blk_shard = NamedSharding(mesh, PartitionSpec(pp_axis))
        self._specs = {
            "pre": {n: PartitionSpec() for n, _ in pre.named_parameters()},
            "blocks": {k: PartitionSpec(pp_axis)
                       for k in dict(blocks[0].named_parameters())},
            "post": {n: PartitionSpec()
                     for n, _ in post.named_parameters()},
        }
        self.params = {
            "pre": {n: jax.device_put(p.data, repl)
                    for n, p in pre.named_parameters()},
            "blocks": {k: jax.device_put(v, blk_shard)
                       for k, v in stack_block_params(blocks).items()},
            "post": {n: jax.device_put(p.data, repl)
                     for n, p in post.named_parameters()},
        }
        self._param_shardings = {
            "pre": {n: repl for n in self.params["pre"]},
            "blocks": {n: blk_shard for n in self.params["blocks"]},
            "post": {n: repl for n in self.params["post"]},
        }
        # read-only buffers (buffer_mode='frozen'): pre/post replicated,
        # block buffers stacked [L, ...] and captured whole (each rank
        # slices its slab by axis_index inside the shard_map program)
        self._frozen_buffers = None
        if self.buffer_mode == "frozen":
            self._frozen_buffers = {
                "pre": {n: jax.device_put(b.data, repl)
                        for n, b in pre.named_buffers() if b is not None},
                "blocks": {k: jax.device_put(v, repl)
                           for k, v in stack_block_buffers(blocks)
                           .items()},
                "post": {n: jax.device_put(b.data, repl)
                         for n, b in post.named_buffers()
                         if b is not None},
            }
        with jax.transfer_guard("allow"):
            opt_state = optimizer.init_state(self.params)

        # opt state inherits the sharding of its param (same shapes)
        def _st_shard(tree, shards):
            return {k: jax.tree_util.tree_map(
                lambda a, s=shards[k]: jax.device_put(a, s), sub)
                for k, sub in tree.items()}
        self.opt_state = {
            bundle: _st_shard(opt_state[bundle],
                              self._param_shardings[bundle])
            for bundle in opt_state}
        # opt-state sharding tree mirrors opt_state (checkpoint restore)
        self._opt_shardings = {
            bundle: {k: jax.tree_util.tree_map(
                lambda a, s=self._param_shardings[bundle][k]: s, sub)
                for k, sub in opt_state[bundle].items()}
            for bundle in opt_state}
        self._blocks_ref = list(blocks)
        self._compiled = None

        # executable observatory + HBM ledger (ISSUE 15): the pipeline
        # tick joins the process exec registry on its first compile
        # (train_step), and the resident params/opt state are tracked
        from ..observability import exec_registry as _exec_registry
        self.telemetry_label = f"g{next(_GPIPE_IDS)}"
        self._exec_component = f"trainer:{self.telemetry_label}"
        _exec_registry.track_bytes(
            self, "params", self.telemetry_label,
            _exec_registry.tree_bytes(self.params))
        _exec_registry.track_bytes(
            self, "opt_state", self.telemetry_label,
            _exec_registry.tree_bytes(self.opt_state))

    # ------------------------------------------------------------------
    def _slice_frozen_buffers(self, idx):
        """(buf_slab, pre_buf, post_buf) for this rank when
        buffer_mode='frozen' (block buffers stacked [L, ...]; each rank
        slices its layer slab), else (None, None, None).  Shared by both
        schedules so the slicing convention cannot diverge."""
        fb = self._frozen_buffers
        if fb is None:
            return None, None, None
        lps = self.num_layers // self.pp_size
        buf_slab = {k: jax.lax.dynamic_slice_in_dim(v, idx * lps, lps, 0)
                    for k, v in fb["blocks"].items()} or None
        return buf_slab, fb["pre"], fb["post"]

    def _head_loss_raw(self, post_p, h, lab_idx, micro_lab, post_buf,
                       training=True):
        """post + user loss for ONE microbatch activation -> f32 scalar
        (un-scaled; router aux NOT included — callers own their
        collector scope and their 1/M conventions).  The single source
        of head/label plumbing for both schedules."""
        out = _call(self.post, post_p, Tensor(h), training=training,
                    buffers=post_buf)
        out_t = jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out)
        lab = jax.tree_util.tree_map(
            lambda a: Tensor(jax.lax.dynamic_index_in_dim(
                a, lab_idx, 0, keepdims=False)), micro_lab)
        lab = lab if isinstance(lab, (list, tuple)) else (lab,)
        l = self.loss_fn(out_t, *lab)
        return (l.data if isinstance(l, Tensor) else l) \
            .astype(jnp.float32)

    def _stage_fn(self, slab, h, training, buf_slab=None):
        """Run this rank's slab of layers: inner scan over [L/S, ...].
        Returns (h, aux): aux losses (MoE routers) produced inside the
        layer scan leave it as explicit scan outputs."""
        from .moe import collect_aux_losses

        def body(carry, xs):
            layer_params, layer_buf = xs if buf_slab is not None \
                else (xs, None)
            with collect_aux_losses() as aux:
                out = _call(self.template, layer_params, carry,
                            training=training, buffers=layer_buf)
            asum = jnp.float32(0.0)
            for a in aux:
                asum = asum + (a.data if isinstance(a, Tensor)
                               else a).astype(jnp.float32)
            return out, asum

        if self.remat:
            body = jax.checkpoint(body)
        xs = (slab, buf_slab) if buf_slab is not None else slab
        h, auxs = jax.lax.scan(body, h, xs)
        return h, jnp.sum(auxs)

    def _pipeline_forward(self, params, micro_in, micro_lab, training):
        """Per-rank program (inside shard_map). micro_in: [M, mb, ...]."""
        S, M = self.pp_size, self.num_micro
        idx = jax.lax.axis_index(self.pp_axis)
        pre_p, slab, post_p = (params["pre"], params["blocks"],
                               params["post"])
        buf_slab, pre_buf, post_buf = self._slice_frozen_buffers(idx)

        def pre_fn(i):
            x = jax.lax.dynamic_index_in_dim(micro_in, i, 0,
                                             keepdims=False)
            return _call(self.pre, pre_p, Tensor(x), training=training,
                         buffers=pre_buf)

        # embed ALL microbatches once, outside the tick loop: the old
        # per-tick pre call ran the embedding M+S-1 times on every rank
        pre_emb = jnp.stack([pre_fn(m) for m in range(M)])  # [M, mb, h]

        h0_aval = pre_emb.shape[1:]
        zero = jnp.zeros(h0_aval, pre_emb.dtype)
        out_buf = jnp.zeros((M,) + h0_aval, pre_emb.dtype)

        def tick(carry, t):
            act, out_buf, aux_acc = carry
            y, aux_t = self._stage_fn(slab, act, training, buf_slab)
            # this rank's tick t holds microbatch (t - idx); bubble ticks
            # run on zeros and their router aux must not count
            valid = (t >= idx) & (t < idx + M)
            aux_acc = aux_acc + jnp.where(valid, aux_t, 0.0)
            out_idx = t - (S - 1)
            write = (idx == S - 1) & (out_idx >= 0)
            slot = jnp.clip(out_idx, 0, M - 1)
            prev = jax.lax.dynamic_index_in_dim(out_buf, slot, 0,
                                                keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(write, y, prev), slot, 0)
            if S > 1:
                y_next = jax.lax.ppermute(
                    y, self.pp_axis, [(i, i + 1) for i in range(S - 1)])
            else:
                y_next = y
            inj = jax.lax.dynamic_index_in_dim(
                pre_emb, jnp.clip(t + 1, 0, M - 1), 0, keepdims=False)
            act = jnp.where(idx == 0, inj, y_next)
            return (act, out_buf, aux_acc), None

        # t counts processed ticks: act entering tick t is stage input
        # for microbatch (t - stage); total M + S - 1 ticks
        init_act = jnp.where(idx == 0, pre_emb[0], zero)
        (act, out_buf, aux_acc), _ = jax.lax.scan(
            tick, (init_act, out_buf, jnp.float32(0.0)),
            jnp.arange(M + S - 1))

        from .moe import collect_aux_losses

        def head_loss(h, lab_idx):
            """post + loss for one microbatch activation h."""
            return self._head_loss_raw(post_p, h, lab_idx, micro_lab,
                                       post_buf, training=training)

        if self.dedupe_head and S > 1:
            # head+loss SHARDED over pp: broadcast the finished
            # activations from the last rank (masked psum = one
            # all-reduce), each rank computes M/S of the heads — per-rank
            # head FLOPs drop S-fold vs the masked-everywhere fallback
            Ms = M // S
            bcast = jax.lax.psum(
                jnp.where(idx == S - 1, out_buf,
                          jnp.zeros_like(out_buf)), self.pp_axis)
            mine = jax.lax.dynamic_slice_in_dim(bcast, idx * Ms, Ms, 0)
            acts = [(mine[j], idx * Ms + j) for j in range(Ms)]
            mask_last = False
        else:
            # fallback: every rank runs all M heads, masked to last rank
            acts = [(out_buf[m], m) for m in range(M)]
            mask_last = True
        with collect_aux_losses() as post_aux:
            losses = [head_loss(h, i) for h, i in acts]
        local = jnp.stack(losses).sum() / M
        for a in post_aux:
            arr = (a.data if isinstance(a, Tensor) else a)
            local = local + arr.astype(jnp.float32) / M
        if mask_last:
            local = jnp.where(idx == S - 1, local, 0.0)
        # block aux: each rank saw every microbatch once -> mean over M
        return (local + aux_acc / M) / self.dp_size

    # ------------------------------------------------------------------
    # 1F1B (PipeDream-flush / Megatron non-interleaved) schedule
    # ------------------------------------------------------------------
    def stash_slots(self) -> int:
        """Per-rank stage-input stash size of the 1F1B schedule: the
        deepest rank keeps 2*(pp-1) microbatch inputs in flight plus the
        one being produced, capped by M.  GPipe's equivalent figure (see
        peak_activation_slots) is M — the whole point of 1F1B."""
        return min(2 * self.pp_size - 1, self.num_micro)

    def peak_activation_slots(self) -> int:
        """Structural peak-activation figure for memory assertions:
        microbatch-sized activation buffers the schedule keeps live per
        rank (1f1b: the input stash; gpipe: the banked-output buffer —
        the scan-transpose residuals it ALSO keeps make this a lower
        bound for gpipe, so the comparison is conservative)."""
        return self.stash_slots() if self.schedule == "1f1b" \
            else self.num_micro

    def _pipeline_1f1b_local(self, params, micro_in, micro_lab):
        """Per-rank 1F1B program (inside shard_map): explicit forward
        AND backward wavefronts in one tick scan — no jax.grad over the
        schedule.  Returns (local_loss, grads) with the same scaling
        conventions as the GPipe path, so the caller's psums are
        identical.

        Clocks (S = pp, M = microbatches, rank = idx):
          forward of microbatch m at tick  m + idx
          backward of microbatch m at tick m + 2*(S-1) - idx
        so the last rank backwards a microbatch the tick its forward
        finishes, and the backward activation-grad reaches rank idx-1
        exactly one tick later (one reverse ppermute per tick).  Total
        ticks: M + 2*(S-1).  Each rank stashes only its stage INPUT per
        in-flight microbatch (stash_slots() of them) and re-runs the
        stage forward inside jax.vjp at the backward tick (activation
        recompute), which is what shrinks peak activation memory from
        GPipe's O(M) to O(pp)."""
        from .moe import collect_aux_losses
        S, M = self.pp_size, self.num_micro
        Q = self.stash_slots()
        T = M + 2 * (S - 1)
        pp, dp_div = self.pp_axis, float(self.dp_size)
        idx = jax.lax.axis_index(pp)
        pre_p, slab, post_p = (params["pre"], params["blocks"],
                               params["post"])
        buf_slab, pre_buf, post_buf = self._slice_frozen_buffers(idx)

        def pre_fn(pp_params, i):
            x = jax.lax.dynamic_index_in_dim(micro_in, i, 0,
                                             keepdims=False)
            return _call(self.pre, pp_params, Tensor(x), training=True,
                         buffers=pre_buf)

        # embed ALL microbatches once (same trade as GPipe: per-tick pre
        # would run T times per rank); these are model INPUTS, not stage
        # activations — the 1F1B memory claim is about the stash below
        pre_emb = jnp.stack([pre_fn(pre_p, m) for m in range(M)])

        def head_scalar(post_params, h, lab_idx):
            """post + loss for one microbatch, scaled 1/M (incl. its
            router aux) — the unit the backward wavefront seeds."""
            with collect_aux_losses() as post_aux:
                l = self._head_loss_raw(post_params, h, lab_idx,
                                        micro_lab, post_buf)
            for a in post_aux:
                l = l + (a.data if isinstance(a, Tensor)
                         else a).astype(jnp.float32)
            return l / M

        def stage_for_vjp(sl, xx):
            return self._stage_fn(sl, xx, True, buf_slab)

        h_shape = pre_emb.shape[1:]
        h_dtype = pre_emb.dtype
        zero_h = jnp.zeros(h_shape, h_dtype)
        zeros_like_tree = lambda t: jax.tree_util.tree_map(
            jnp.zeros_like, t)
        # grad deltas come out of lax.cond branches whose false side is
        # exact zeros — a plain add accumulates them, no re-masking
        tree_add = lambda acc, d: jax.tree_util.tree_map(jnp.add, acc, d)

        def tick(carry, t):
            (act, gy, stash, dslab, dpre, dpost, loss_acc,
             aux_acc) = carry
            # bubble ticks and non-owning ranks skip their halves at
            # RUNTIME via lax.cond (per-device control flow is legal
            # under shard_map, and nothing here is differentiated from
            # outside — the backward is already explicit), instead of
            # computing garbage and masking it: at pp=4/M=8 the masked
            # formulation ran 13 head+embedding vjps per rank where 8
            # (resp. 8 on rank 0 only) are real.
            # ---- forward half: one microbatch through my slab --------
            valid_f = (t >= idx) & (t < idx + M)
            y, aux_t = jax.lax.cond(
                valid_f,
                lambda a: self._stage_fn(slab, a, True, buf_slab),
                lambda a: (jnp.zeros_like(a), jnp.float32(0.0)), act)
            aux_acc = aux_acc + aux_t
            mf = jnp.clip(t - idx, 0, M - 1)
            slot_f = jnp.mod(mf, Q)
            kept = jax.lax.dynamic_index_in_dim(stash, slot_f, 0,
                                                keepdims=False)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(valid_f, act, kept), slot_f, 0)
            # ---- backward half: the trailing wavefront ---------------
            mb = t - 2 * (S - 1) + idx
            valid_b = (mb >= 0) & (mb < M)
            mbc = jnp.clip(mb, 0, M - 1)
            x_saved = jax.lax.dynamic_index_in_dim(
                stash, jnp.mod(mbc, Q), 0, keepdims=False)
            is_last = idx == S - 1
            # last rank: this tick's y IS microbatch mb's finished stage
            # output (the clocks coincide there) — seed the backward
            # with the loss gradient and bank the loss value
            take_head = valid_b & is_last

            def head_branch(y_):
                lm, head_vjp = jax.vjp(
                    lambda hp, hh: head_scalar(hp, hh, mbc), post_p, y_)
                dpost_t, dy = head_vjp(jnp.asarray(1.0 / dp_div,
                                                   jnp.float32))
                return lm, dpost_t, dy

            lm, dpost_t, dy = jax.lax.cond(
                take_head, head_branch,
                lambda y_: (jnp.float32(0.0), zeros_like_tree(post_p),
                            jnp.zeros_like(y_)), y)
            loss_acc = loss_acc + lm / dp_div
            dpost = tree_add(dpost, dpost_t)
            gy_eff = jnp.where(is_last, dy.astype(h_dtype), gy)
            # stage backward by recompute: vjp wrt (slab, stage input);
            # the aux cotangent routes the router losses' grads

            def bwd_branch(op):
                gy_, x_ = op
                _, stage_vjp = jax.vjp(stage_for_vjp, slab, x_)
                return stage_vjp(
                    (gy_, jnp.float32(1.0 / (M * dp_div))))

            dslab_t, dx = jax.lax.cond(
                valid_b, bwd_branch,
                lambda op: (zeros_like_tree(slab),
                            jnp.zeros_like(op[1])), (gy_eff, x_saved))
            dslab = tree_add(dslab, dslab_t)
            # rank 0 owns the embedding backward for its microbatch
            take_pre = valid_b & (idx == 0)

            def pre_branch(dx_):
                _, pre_vjp = jax.vjp(lambda hp: pre_fn(hp, mbc), pre_p)
                (dpre_t,) = pre_vjp(dx_)
                return dpre_t

            dpre_t = jax.lax.cond(
                take_pre, pre_branch,
                lambda dx_: zeros_like_tree(pre_p), dx)
            dpre = tree_add(dpre, dpre_t)
            # ---- stage-boundary traffic for the next tick ------------
            if S > 1:
                y_next = _mesh.ppermute(
                    y, pp, [(i, i + 1) for i in range(S - 1)])
                gy_next = _mesh.ppermute(
                    dx, pp, [(i, i - 1) for i in range(1, S)])
            else:
                y_next, gy_next = y, dx
            inj = jax.lax.dynamic_index_in_dim(
                pre_emb, jnp.clip(t + 1, 0, M - 1), 0, keepdims=False)
            act = jnp.where(idx == 0, inj, y_next)
            return (act, gy_next, stash, dslab, dpre, dpost, loss_acc,
                    aux_acc), None

        init = (jnp.where(idx == 0, pre_emb[0], zero_h),
                zero_h,
                jnp.zeros((Q,) + h_shape, h_dtype),
                zeros_like_tree(slab), zeros_like_tree(pre_p),
                zeros_like_tree(post_p),
                jnp.float32(0.0), jnp.float32(0.0))
        (act, gy, stash, dslab, dpre, dpost, loss_acc, aux_acc), _ = \
            jax.lax.scan(tick, init, jnp.arange(T))
        local = loss_acc + aux_acc / (M * dp_div)
        return local, {"pre": dpre, "blocks": dslab, "post": dpost}

    def _build_1f1b(self):
        mesh = self.mesh
        P = PartitionSpec
        pp, dp = self.pp_axis, self.dp_axis
        has_dp = self.dp_size > 1
        in_specs_params = {
            "pre": self._specs["pre"], "blocks": self._specs["blocks"],
            "post": self._specs["post"]}
        batch_spec = P(None, dp) if has_dp else P()

        def local_step(params, micro_in, micro_lab):
            local, grads = self._pipeline_1f1b_local(
                params, micro_in, micro_lab)
            axes_repl = (pp, dp) if has_dp else (pp,)
            grads = {
                "pre": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axes_repl), grads["pre"]),
                "blocks": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, dp) if has_dp else g,
                    grads["blocks"]),
                "post": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axes_repl), grads["post"]),
            }
            loss = jax.lax.psum(local, axes_repl)
            return loss, grads

        smapped = shard_map(
            local_step, mesh=mesh,
            in_specs=(in_specs_params, batch_spec, batch_spec),
            out_specs=(P(), dict(in_specs_params)),
            check_vma=False)

        def step(params, opt_state, lr, step_no, micro_in, micro_lab):
            loss, grads = smapped(params, micro_in, micro_lab)
            new_params, new_opt = self.optimizer.apply_gradients(
                params, grads, opt_state, lr=lr, step=step_no)
            return new_params, new_opt, loss

        return jax.jit(
            step,
            out_shardings=(self._param_shardings, None, None),
            donate_argnums=(0, 1))

    def _build(self, training=True):
        if self.schedule == "1f1b" and training:
            return self._build_1f1b()
        mesh = self.mesh
        P = PartitionSpec
        pp, dp = self.pp_axis, self.dp_axis
        has_dp = self.dp_size > 1

        in_specs_params = {
            "pre": self._specs["pre"], "blocks": self._specs["blocks"],
            "post": self._specs["post"]}
        batch_spec = P(None, dp) if has_dp else P()

        def local_step(params, micro_in, micro_lab):
            def lfn(ps):
                return self._pipeline_forward(ps, micro_in, micro_lab,
                                              training)
            loss, grads = jax.value_and_grad(lfn)(params)
            # replicated pre/post: contributions live on specific pp
            # ranks — sum them; slab grads are rank-local over pp
            axes_repl = (pp, dp) if has_dp else (pp,)
            grads = {
                "pre": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axes_repl), grads["pre"]),
                "blocks": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, dp) if has_dp else g,
                    grads["blocks"]),
                "post": jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axes_repl), grads["post"]),
            }
            loss = jax.lax.psum(loss, axes_repl)
            return loss, grads

        grad_specs = dict(in_specs_params)
        smapped = shard_map(
            local_step, mesh=mesh,
            in_specs=(in_specs_params, batch_spec, batch_spec),
            out_specs=(P(), grad_specs),
            check_vma=False)

        def step(params, opt_state, lr, step_no, micro_in, micro_lab):
            loss, grads = smapped(params, micro_in, micro_lab)
            new_params, new_opt = self.optimizer.apply_gradients(
                params, grads, opt_state, lr=lr, step=step_no)
            return new_params, new_opt, loss

        return jax.jit(
            step,
            out_shardings=(self._param_shardings, None, None),
            donate_argnums=(0, 1))

    # ------------------------------------------------------------------
    def _microbatch(self, arr):
        """[B, ...] -> [M, B/M, ...] host-side split + device_put sharded
        over dp on the microbatch dim."""
        a = arr.data if isinstance(arr, Tensor) else jnp.asarray(arr)
        b = a.shape[0]
        if b % self.num_micro:
            # a silent truncation here would drop samples from every
            # step — refuse loudly instead (drop the remainder yourself
            # or pick a num_microbatches that divides the batch)
            raise ValueError(
                f"batch size {b} is not divisible by num_microbatches="
                f"{self.num_micro}: the pipeline schedule needs equal "
                f"microbatches. Pad or trim the batch to a multiple of "
                f"{self.num_micro}, or construct the trainer with a "
                f"num_microbatches that divides {b}.")
        mb = a.reshape((self.num_micro, b // self.num_micro) + a.shape[1:])
        spec = PartitionSpec(
            None, self.dp_axis if (self.dp_size > 1 and
                                   mb.shape[1] % self.dp_size == 0)
            else None, *([None] * (mb.ndim - 2)))
        return jax.device_put(mb, NamedSharding(self.mesh, spec))

    def train_step(self, inputs, labels):
        # stall watchdog (PADDLE_TPU_WATCHDOG_S): armed on first step,
        # one heartbeat per step afterwards
        if not self._wd_checked:
            self._wd_checked = True
            from ..observability import watchdog as _wd
            t = _wd.watchdog_seconds()
            if t is not None:
                self.watchdog = _wd.Watchdog(t, label="gpipe_train").arm()
        if self.watchdog is not None:
            self.watchdog.beat()
        if self._profile is not None:
            self._profile.on_step(self._step_count)
        micro_in = self._microbatch(inputs)
        micro_lab = jax.tree_util.tree_map(
            self._microbatch, labels,
            is_leaf=lambda x: isinstance(x, Tensor))
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._step_count + 1, jnp.int32)
        first = self._compiled is None
        if first:
            self._compiled = self._build(training=True)
            if self._comm_enabled:
                # AOT collective breakdown while the args are alive (the
                # real call donates params/opt_state)
                from ..utils import comm_stats as _cs
                self._comm = _cs.analyze_jit(
                    self._compiled, self.params, self.opt_state, lr,
                    step_no, micro_in, micro_lab,
                    device=self.mesh.devices.flat[0])
            from ..observability import exec_registry as _exec_registry
            if _exec_registry.enabled():
                # join the executable observatory pre-call (the step
                # donates params/opt_state; shape structs must be
                # captured while the buffers are readable)
                _exec_registry.register(
                    self._exec_component, "tick", "train_step",
                    jitfn=self._compiled,
                    args=(self.params, self.opt_state, lr, step_no,
                          micro_in, micro_lab),
                    donate_argnums=(0, 1),
                    meta={"schedule": self.schedule,
                          "pp_size": self.pp_size,
                          "num_microbatches": self.num_micro})
        t0 = time.perf_counter()
        self.params, self.opt_state, loss = self._compiled(
            self.params, self.opt_state, lr, step_no, micro_in, micro_lab)
        dt = (time.perf_counter() - t0) * 1e3
        if first:
            self._timings["compile_ms_cold"] += dt
            from ..observability import exec_registry as _exec_registry
            _exec_registry.registry().note_compile(
                self._exec_component, "tick", dt)
        else:
            self._timings["dispatch_ms"] += dt
            self._timings["steps_timed"] += 1
            from ..observability import exec_registry as _exec_registry
            _exec_registry.note_runtime(self._exec_component, "tick", dt)
        self._step_count += 1
        self.optimizer._step_count = self._step_count
        # deterministic preemption point (PADDLE_FAULT_SIGTERM_STEP) —
        # the pipeline trainer is part of the kill-and-resume story too
        from ..testing import faults as _faults
        _faults.maybe_sigterm(self._step_count)
        _faults.maybe_hang(self._step_count)
        self.step_timer.tick()
        self._m_steps.inc()
        if self.step_timer.last_ms is not None:
            self._m_step_ms.set(self.step_timer.last_ms)
            self._m_step_hist.observe(self.step_timer.last_ms)
        from ..observability import flightrec as _flightrec
        _flightrec.record("train_step", dur_ms=self.step_timer.last_ms,
                          step=self._step_count, trainer="gpipe")
        return loss

    @property
    def stats(self) -> dict:
        """Schedule + step-time + collective breakdown (the pipeline
        mirror of SpmdTrainer.stats; comm fields need comm_stats=True /
        PADDLE_TPU_COMM_STATS=1)."""
        s = {"schedule": self.schedule,
             "num_microbatches": self.num_micro,
             "pp_size": self.pp_size,
             "peak_activation_slots": self.peak_activation_slots(),
             "resume_elastic": self.resume_elastic,
             "reshard_restores": self._reshard_restores}
        for k, v in self._timings.items():
            s[k] = round(v, 3) if isinstance(v, float) else v
        s["step_time_ms"] = round(self.step_timer.last_ms, 3) \
            if self.step_timer.last_ms is not None else None
        s["step_time_mean_ms"] = round(self.step_timer.mean_ms, 3) \
            if self.step_timer.mean_ms is not None else None
        res = self._comm
        s["comm_ms"] = res["comm_ms"] if res else None
        s["comm_bytes"] = res["bytes"] if res else None
        s["comm_collectives"] = res["count"] if res else None
        s["comm_by_op"] = res["by_op"] if res else None
        steps = self._timings["steps_timed"]
        mean_step = (self._timings["dispatch_ms"] / steps) if steps \
            else 0.0
        s["comm_fraction"] = round(res["comm_ms"] / mean_step, 4) \
            if (res and mean_step > 0) else None
        from ..observability import doctor as _doctor
        from ..observability import exec_registry as _exec_registry
        s["exec_profile"] = _exec_registry.profile(self._exec_component)
        s["hbm"] = _exec_registry.ledger().snapshot()
        s["doctor"] = _doctor.diagnose(s, kind="train")
        return s

    # ------------------------------------------------------------------
    def save(self, path: str, extra=None, manifest: bool = False) -> str:
        """Checkpoint params + opt state + step (see SpmdTrainer.save).
        manifest=True writes the integrity-checked directory format
        whose v2 metadata records the pp/dp topology for elastic
        restores."""
        from .checkpoint import save_trainer
        return save_trainer(self, path, extra=extra, manifest=manifest)

    def load(self, path: str) -> dict:
        """Restore a save() checkpoint; a checkpoint written on a
        different (pp, dp) mesh reshards onto THIS trainer's mesh
        (stage slabs re-split over the new pp extent) unless
        resume_elastic=False."""
        from .checkpoint import load_trainer
        return load_trainer(self, path)

    # ------------------------------------------------------------------
    def sync_to_model(self):
        """Write trained arrays back into the source layers (unstacking
        the block slabs)."""
        for n, p in self.pre.named_parameters():
            p._data = self.params["pre"][n]
        for n, p in self.post.named_parameters():
            p._data = self.params["post"][n]
        for k, stacked in self.params["blocks"].items():
            host = np.asarray(stacked)
            for i, blk in enumerate(self._blocks_ref):
                dict(blk.named_parameters())[k]._data = jnp.asarray(host[i])
        return self
