"""Minimal host-side parameter server.

Reference: /root/reference/paddle/fluid/distributed/service/
(brpc_ps_server.h PSServer, ps_client.h PSClient) +
table/common_dense_table.h / common_sparse_table.cc (the dense and
sparse tables with per-table optimizer rules).

Scope and TPU-native rationale: collective SPMD training over a mesh is
this framework's primary scaling path (the reference's PS mode predates
its collective mode and serves sparse-CTR workloads). This PS covers
that workload class host-side: dense + id-keyed sparse tables with
per-table SGD/Adagrad/Adam rules, served over a length-prefixed TCP
protocol; trainers push gradients and pull fresh parameters fully
asynchronously (a_sync mode, reference AsyncCommunicator) — dense HBM
math stays on the TPU, the big sparse tables stay in host DRAM where
they belong.
"""
from .table import DenseTable, SparseTable, sgd_rule, adagrad_rule, adam_rule  # noqa: F401
from .server import PSServer  # noqa: F401
from .client import PSClient  # noqa: F401

__all__ = ["DenseTable", "SparseTable", "PSServer", "PSClient",
           "sgd_rule", "adagrad_rule", "adam_rule"]
