"""PS client: shards tables over servers, talks the pickle protocol.

Reference: ps_client.h / brpc_ps_client.cc (PSClient: pull_dense /
push_dense_param / pull_sparse / push_sparse against N server shards).
Sharding follows the reference: dense tables live whole on
hash(name) % n_servers; sparse rows scatter by id % n_servers.
"""
from __future__ import annotations

import socket
import threading
import zlib
from typing import Dict, List, Sequence

import numpy as np

from .server import recv_msg, send_msg

__all__ = ["PSClient"]


class _Conn:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=60)
        self.lock = threading.Lock()

    def call(self, msg):
        with self.lock:
            send_msg(self.sock, msg)
            reply = recv_msg(self.sock)
        if reply is None:
            raise ConnectionError("PS server closed the connection")
        status, payload = reply
        if status != "ok":
            raise RuntimeError(f"PS server error: {payload}")
        return payload

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class PSClient:
    def __init__(self, endpoints: Sequence[str]):
        if not endpoints:
            raise ValueError("PSClient needs at least one server endpoint")
        self.endpoints = list(endpoints)
        self._conns: List[_Conn] = [_Conn(e) for e in self.endpoints]
        self._dense_home: Dict[str, int] = {}
        self._sparse_dims: Dict[str, int] = {}
        # shard fan-out pool: the reference PSClient issues the per-shard
        # RPCs concurrently; a serial loop would pay n_servers RTTs per
        # training step
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=max(len(self._conns), 1),
            thread_name_prefix="ps-client")

    # ---- placement ----------------------------------------------------
    def _dense_conn(self, name: str) -> _Conn:
        if name not in self._dense_home:
            self._dense_home[name] = zlib.crc32(name.encode()) % \
                len(self._conns)
        return self._conns[self._dense_home[name]]

    # ---- table management ---------------------------------------------
    def ensure_dense_table(self, name: str, shape, rule="sgd", init=None,
                           seed=0):
        spec = {"shape": tuple(shape), "rule": rule, "seed": seed}
        if init is not None:
            spec["init"] = np.asarray(init, np.float32)
        self._dense_conn(name).call(("ensure_table", name, "dense", spec))

    def ensure_sparse_table(self, name: str, dim: int, rule="sgd",
                            init_scale=0.01, seed=0):
        spec = {"dim": int(dim), "rule": rule, "init_scale": init_scale,
                "seed": seed}
        msg = ("ensure_table", name, "sparse", spec)
        # every shard holds part of the id space
        list(self._pool.map(lambda c: c.call(msg), self._conns))
        self._sparse_dims[name] = int(dim)

    def _sparse_dim(self, name: str) -> int:
        if name not in self._sparse_dims:
            self._sparse_dims[name] = int(
                self._conns[0].call(("table_dim", name)))
        return self._sparse_dims[name]

    # ---- dense --------------------------------------------------------
    def pull_dense(self, name: str) -> np.ndarray:
        return self._dense_conn(name).call(("pull_dense", name))

    def push_dense(self, name: str, grad, lr: float):
        self._dense_conn(name).call(
            ("push_dense", name, np.asarray(grad, np.float32), float(lr)))

    # ---- sparse -------------------------------------------------------
    def pull_sparse(self, name: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if len(ids) == 0:
            return np.empty((0, self._sparse_dim(name)), np.float32)
        n = len(self._conns)
        shard_of = ids % n
        jobs = []
        for s in range(n):
            mask = shard_of == s
            if mask.any():
                pos = np.nonzero(mask)[0]
                jobs.append((pos, self._pool.submit(
                    self._conns[s].call, ("pull_sparse", name, ids[mask]))))
        first = jobs[0][1].result()
        out = np.empty((len(ids), first.shape[1]), np.float32)
        for pos, fut in jobs:
            out[pos] = fut.result()
        return out

    def push_sparse(self, name: str, ids, grads, lr: float):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        n = len(self._conns)
        shard_of = ids % n
        futs = []
        for s in range(n):
            mask = shard_of == s
            if mask.any():
                futs.append(self._pool.submit(
                    self._conns[s].call,
                    ("push_sparse", name, ids[mask], grads[mask],
                     float(lr))))
        for f in futs:
            f.result()

    # ---- control ------------------------------------------------------
    def barrier(self):
        # barrier against shard 0 (all workers rendezvous in one place)
        self._conns[0].call(("barrier",))

    def sparse_table_size(self, name: str) -> int:
        return sum(c.call(("table_size", name)) for c in self._conns)

    def stop_all_servers(self):
        for c in self._conns:
            try:
                c.call(("stop",))
            except (ConnectionError, OSError):
                pass

    def close(self):
        self._pool.shutdown(wait=False)
        for c in self._conns:
            c.close()
