"""PS tables: dense and id-keyed sparse with pluggable optimizer rules.

Reference: table/common_dense_table.h (dense values + sgd rule),
common_sparse_table.cc (shard of id -> [value | optimizer-state] rows,
rows materialize on first access with a configured initializer).

Host-side numpy on purpose: these tables live in server DRAM and are
touched a few rows at a time — the TPU never sees them whole.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import numpy as np

__all__ = ["DenseTable", "SparseTable", "sgd_rule", "adagrad_rule",
           "adam_rule"]


# ---- optimizer rules ------------------------------------------------------
# A rule is (state_factory, apply): state_factory(shape) -> dict of
# state arrays; apply(value, grad, state, lr) mutates value/state inplace.

def _sgd_apply(v, g, s, lr):
    v -= lr * g


def sgd_rule():
    return (lambda shape: {}, _sgd_apply)


def _adagrad_state(shape):
    return {"g2": np.zeros(shape, np.float32)}


def _adagrad_apply(v, g, s, lr, eps=1e-6):
    s["g2"] += g * g
    v -= lr * g / (np.sqrt(s["g2"]) + eps)


def _adam_state(shape):
    return {"m": np.zeros(shape, np.float32),
            "v2": np.zeros(shape, np.float32),
            "t": np.zeros((), np.int64)}


def _adam_apply(v, g, s, lr, b1=0.9, b2=0.999, eps=1e-8):
    s["t"] += 1
    t = int(s["t"])
    s["m"] = b1 * s["m"] + (1 - b1) * g
    s["v2"] = b2 * s["v2"] + (1 - b2) * g * g
    mhat = s["m"] / (1 - b1 ** t)
    vhat = s["v2"] / (1 - b2 ** t)
    v -= lr * mhat / (np.sqrt(vhat) + eps)


def adagrad_rule():
    return (_adagrad_state, _adagrad_apply)


def adam_rule():
    return (_adam_state, _adam_apply)


_RULES = {"sgd": sgd_rule, "adagrad": adagrad_rule, "adam": adam_rule}


def get_rule(name: str):
    if name not in _RULES:
        raise ValueError(f"unknown PS optimizer rule {name!r}; "
                         f"have {sorted(_RULES)}")
    return _RULES[name]()


# ---- tables ---------------------------------------------------------------
class DenseTable:
    """Flat dense parameter block (common_dense_table.h role)."""

    kind = "dense"

    def __init__(self, shape, rule: str = "sgd",
                 init: Optional[np.ndarray] = None, seed: int = 0):
        self.shape = tuple(shape)
        if init is not None:
            self.value = np.array(init, np.float32).reshape(self.shape)
        else:
            rng = np.random.RandomState(seed)
            self.value = (rng.randn(*self.shape) * 0.01).astype(np.float32)
        self._state_factory, self._apply = get_rule(rule)
        self._state = self._state_factory(self.shape)
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.value.copy()

    def push(self, grad: np.ndarray, lr: float):
        g = np.asarray(grad, np.float32).reshape(self.shape)
        with self._lock:
            self._apply(self.value, g, self._state, lr)


class SparseTable:
    """id -> row table; rows materialize on first touch
    (common_sparse_table.cc shard semantics)."""

    kind = "sparse"

    def __init__(self, dim: int, rule: str = "sgd", init_scale: float = 0.01,
                 seed: int = 0):
        self.dim = int(dim)
        self.init_scale = float(init_scale)
        self._seed = int(seed)
        self._rows: Dict[int, np.ndarray] = {}
        self._states: Dict[int, dict] = {}
        self._state_factory, self._apply = get_rule(rule)
        self._lock = threading.Lock()

    def _row(self, i: int) -> np.ndarray:
        r = self._rows.get(i)
        if r is None:
            # deterministic per-id init so every server shard agrees
            rng = np.random.RandomState((self._seed * 1000003 + i)
                                        & 0x7FFFFFFF)
            r = (rng.randn(self.dim) * self.init_scale).astype(np.float32)
            self._rows[i] = r
            self._states[i] = self._state_factory((self.dim,))
        return r

    def pull(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads, lr: float):
        ids = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        # aggregate duplicate ids first (MergeAdd) so the rule sees one
        # gradient per row, like the reference's merged push
        order: Dict[int, np.ndarray] = {}
        for i, gi in zip(ids, g):
            i = int(i)
            order[i] = order[i] + gi if i in order else gi.copy()
        with self._lock:
            for i, gi in order.items():
                self._apply(self._row(i), gi, self._states[i], lr)

    def size(self) -> int:
        with self._lock:
            return len(self._rows)
