"""PS server: threaded TCP service over a length-prefixed pickle protocol.

Reference: brpc_ps_server.h (BrpcPsServer: an RPC service dispatching
pull_dense / push_dense_param / pull_sparse / push_sparse to tables) —
rebuilt on the standard-library socketserver instead of brpc; the
protocol is 8-byte big-endian length + HMAC-SHA256 tag + pickled
(cmd, *args) tuples, matching the launcher's plain-TCP transport.

SECURITY: pickle over a socket is code execution for anyone who can
write to it.  Every frame therefore carries an HMAC over the payload
keyed by the PADDLE_PS_SECRET env var (the launcher distributes it to
the pod like the reference's trainer env contract); frames with a bad
tag are dropped before unpickling.  Binding a non-loopback address
without a secret is refused outright.  Frame size is capped to stop a
forged length prefix from OOMing the server.

Async semantics (a_sync mode / AsyncCommunicator): every trainer's push
applies immediately under the table lock — no cross-trainer barrier on
the hot path. barrier() is available for epoch boundaries (reference
_barrier worker semantics).
"""
from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional, Tuple

from .table import DenseTable, SparseTable

__all__ = ["PSServer", "send_msg", "recv_msg"]

_LEN = struct.Struct(">Q")
_TAG_BYTES = 32
MAX_FRAME = 1 << 31  # 2 GiB: far above any sane pull/push


def _secret() -> bytes:
    return os.environ.get("PADDLE_PS_SECRET", "").encode()


def send_msg(sock: socket.socket, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    tag = hmac.new(_secret(), payload, hashlib.sha256).digest()
    sock.sendall(_LEN.pack(len(payload)) + tag + payload)


def recv_msg(sock: socket.socket):
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ConnectionError(f"PS frame length {n} exceeds MAX_FRAME")
    tag = _recv_exact(sock, _TAG_BYTES)
    if tag is None:
        return None
    body = _recv_exact(sock, n)
    if body is None:
        return None
    want = hmac.new(_secret(), body, hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        raise ConnectionError(
            "PS frame failed HMAC authentication (PADDLE_PS_SECRET "
            "mismatch or untrusted sender)")
    return pickle.loads(body)


def _recv_exact(sock, n) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: "PSServer" = self.server.ps  # type: ignore[attr-defined]
        while True:
            msg = recv_msg(self.request)
            if msg is None:
                return
            try:
                reply = srv.dispatch(msg)
            except Exception as e:  # surface server errors to the client
                reply = ("err", f"{type(e).__name__}: {e}")
            send_msg(self.request, reply)
            if msg[0] == "stop":
                return


class _TCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PSServer:
    """One PS shard: tables + the request dispatcher."""

    def __init__(self, endpoint: str, n_workers: int = 1):
        host, port = endpoint.rsplit(":", 1)
        if host not in ("127.0.0.1", "localhost", "::1") and not _secret():
            raise RuntimeError(
                "refusing to serve pickled frames on a non-loopback "
                f"address ({host}) without PADDLE_PS_SECRET set — the "
                "HMAC is the only thing keeping arbitrary hosts from "
                "executing code via pickle")
        self.endpoint = endpoint
        self.n_workers = int(n_workers)
        self.tables: Dict[str, object] = {}
        self._tables_lock = threading.Lock()
        self._tcp = _TCP((host, int(port)), _Handler)
        self._tcp.ps = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._barrier_lock = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    # ---- lifecycle ----------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def run(self):
        """Blocking serve (fleet.run_server role)."""
        self.start()
        self._stop_evt.wait()
        self._tcp.shutdown()

    def stop(self):
        self._stop_evt.set()
        self._tcp.shutdown()
        self._tcp.server_close()

    # ---- dispatch -----------------------------------------------------
    def dispatch(self, msg: Tuple):
        cmd, *args = msg
        if cmd == "ensure_table":
            name, kind, spec = args
            with self._tables_lock:  # concurrent workers both ensure
                if name not in self.tables:
                    if kind == "dense":
                        self.tables[name] = DenseTable(**spec)
                    elif kind == "sparse":
                        self.tables[name] = SparseTable(**spec)
                    else:
                        raise ValueError(f"unknown table kind {kind}")
            return ("ok", None)
        if cmd == "pull_dense":
            (name,) = args
            return ("ok", self.tables[name].pull())
        if cmd == "push_dense":
            name, grad, lr = args
            self.tables[name].push(grad, lr)
            return ("ok", None)
        if cmd == "pull_sparse":
            name, ids = args
            return ("ok", self.tables[name].pull(ids))
        if cmd == "push_sparse":
            name, ids, grads, lr = args
            self.tables[name].push(ids, grads, lr)
            return ("ok", None)
        if cmd == "barrier":
            return self._barrier()
        if cmd == "table_size":
            (name,) = args
            t = self.tables[name]
            return ("ok", t.size() if isinstance(t, SparseTable)
                    else t.shape)
        if cmd == "table_dim":
            (name,) = args
            t = self.tables[name]
            return ("ok", t.dim if isinstance(t, SparseTable)
                    else t.shape)
        if cmd == "stop":
            threading.Thread(target=self.stop, daemon=True).start()
            return ("ok", None)
        raise ValueError(f"unknown PS command {cmd!r}")

    def _barrier(self):
        """Block until n_workers calls arrive (reference barrier_worker).
        A timeout (a peer died) un-registers this waiter and returns an
        error so the caller cannot proceed unsynchronized — and the
        count stays consistent for the next round."""
        with self._barrier_lock:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self.n_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_lock.notify_all()
                return ("ok", None)
            released = self._barrier_lock.wait_for(
                lambda: self._barrier_gen != gen, timeout=120)
            if not released:
                self._barrier_count -= 1
                return ("err", "barrier timed out after 120s "
                               "(a worker likely died)")
        return ("ok", None)
