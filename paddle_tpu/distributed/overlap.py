"""Latency-hiding collectives — the one knob and its defaults.

``PADDLE_TPU_OVERLAP`` governs every communication-overlap schedule in
the framework (default ON; set ``0`` to force every schedule back to its
synchronous counterpart for A/B runs):

- ZeRO-3 overlapped parameter all-gather (`distributed.zero3`, wired by
  SpmdTrainer when ``sharding_configs={'stage': 3}`` + scan-over-layers);
- the 1F1B pipeline schedule default (`distributed.pipeline`,
  ``schedule=None`` resolves here);
- chunked MoE all-to-all (`distributed.moe`, ``a2a_chunks=None``
  resolves here);
- the XLA async-collective / latency-hiding-scheduler flags on real
  accelerator backends (`ensure_xla_overlap_flags`).

All of the schedules are numerics-preserving (they reorder communication,
not math); the dryrun and tests assert loss parity against the
synchronous paths, so the default can be ON.
"""
from __future__ import annotations

import os
import sys

__all__ = ["overlap_enabled", "pipeline_schedule_default",
           "moe_a2a_chunks", "autotune_a2a_sweep",
           "ensure_xla_overlap_flags"]


def overlap_enabled() -> bool:
    """The master knob: PADDLE_TPU_OVERLAP (default on)."""
    return os.environ.get("PADDLE_TPU_OVERLAP", "1") != "0"


def pipeline_schedule_default() -> str:
    """Schedule used when GPipeTrainer(schedule=None):
    PADDLE_TPU_PIPELINE_SCHEDULE if set, else 'gpipe'.  1F1B is chosen
    per-constructor (schedule='1f1b') or via the env var — it computes
    the same losses but its explicit interleaved backward is a different
    compiled program, so flipping an existing trainer's schedule is an
    intentional act, not an ambient default.

    PADDLE_TPU_OVERLAP=0 overrides the env-var schedule back to 'gpipe'
    (the documented 'every schedule falls back to its synchronous
    counterpart' contract — an A/B flip of the one knob must actually
    change the program); an explicit constructor argument still wins
    over both."""
    if not overlap_enabled():
        return "gpipe"
    return os.environ.get("PADDLE_TPU_PIPELINE_SCHEDULE") or "gpipe"


def moe_a2a_chunks(tokens: int) -> int:
    """Chunk count for the MoE shard_map all-to-all when the layer was
    built with ``a2a_chunks=None``: PADDLE_TPU_MOE_A2A_CHUNKS if set,
    else the unified tuning table (utils.tuning, op "moe_a2a_chunks",
    key (device_kind, tokens) — recorded by a sweep or an operator),
    else 2 (so chunk j's exchange can overlap chunk j-1's expert FFN).
    PADDLE_TPU_OVERLAP=0 forces 1 (monolithic) EVEN IF the chunk env
    var is set — the kill switch must win over every env-selected
    schedule or an A/B of the one knob measures nothing (only an
    explicit MoELayer(a2a_chunks=...) argument overrides it).  Always
    clamped to a divisor of `tokens` (the per-expert token-slot count)
    — a ragged chunk would change shapes, and shape stability is the
    recompile-free contract."""
    if not overlap_enabled():
        return 1
    want = int(os.environ.get("PADDLE_TPU_MOE_A2A_CHUNKS", "0"))
    if not want:
        try:
            from ..utils import tuning as _tuning
            key = (_tuning.device_kind(), tokens)
            tuned = _tuning.lookup("moe_a2a_chunks", key)
            if tuned is None:
                # the sweep measures at the BENCH shape; a MoE layer's
                # b×capacity token count rarely equals it exactly —
                # nearest tabled count (same device, within ~4× either
                # way) still beats the blind default
                tuned = _tuning.lookup_nearest(
                    "moe_a2a_chunks", key, match_idx=(0,),
                    near_idx=(1,), max_dist=1.4)
            if tuned is not None:
                want = int(tuned)
        except (ValueError, TypeError):
            pass
    want = want or 2
    want = max(1, min(want, tokens if tokens > 0 else 1))
    while tokens % want:
        want -= 1
    return want


def autotune_a2a_sweep(tokens: int, hidden: int = 512, iters: int = 5):
    """On-device sweep of the MoE all-to-all chunk count: time a
    chunked token exchange (split → K sequential all_to_alls → concat,
    the dispatch shape distributed.moe uses) for K in (1, 2, 4, 8) over
    the local devices and record the winner in the unified tuning table
    (op "moe_a2a_chunks", key (device_kind, tokens)) so
    :func:`moe_a2a_chunks` serves it to every later process.  Needs >1
    device; returns the winning K or None."""
    import time

    import numpy as np
    import jax
    import jax.numpy as jnp

    from ..utils import tuning as _tuning
    from .mesh import shard_map as _shard_map

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        return None
    # per-device token rows, rounded so every candidate K divides them
    t_loc = max(tokens // n, 8 * n)
    t_loc -= t_loc % (8 * n)
    mesh = jax.sharding.Mesh(np.array(devs), ("x",))
    spec = jax.sharding.PartitionSpec("x")
    x = jnp.zeros((n * t_loc, hidden), jnp.float32)

    def chunked(arr, k):
        def body(xs):                     # local shard [t_loc, hidden]
            parts = jnp.split(xs, k, axis=0)
            outs = [jax.lax.all_to_all(
                p.reshape(n, -1, hidden), "x", 0, 0, tiled=False)
                .reshape(-1, hidden) for p in parts]
            return jnp.concatenate(outs, axis=0)
        return _shard_map(body, mesh=mesh, in_specs=spec,
                          out_specs=spec)(arr)

    best, best_t = None, None
    for k in (1, 2, 4, 8):
        if t_loc % (k * n):
            continue
        try:
            fn = jax.jit(lambda a, k=k: chunked(a, k))
            jax.block_until_ready(fn(x))
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = fn(x)
            jax.block_until_ready(out)
            t = (time.perf_counter() - t0) / iters
        except Exception:
            continue
        if best_t is None or t < best_t:
            best, best_t = k, t
    if best is not None:
        # record under the token count actually timed (t_loc was
        # rounded for divisibility), not the requested one
        _tuning.record("moe_a2a_chunks",
                       (_tuning.device_kind(), n * t_loc), best)
    return best


# XLA flags that let the compiler's latency-hiding scheduler run
# collectives asynchronously behind compute.  Only meaningful (and only
# RECOGNIZED) on real accelerator backends — the CPU backend rejects
# unknown flags at startup, so these are gated on the declared platform.
_TPU_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
)
_GPU_FLAGS = (
    "--xla_gpu_enable_latency_hiding_scheduler=true",
    "--xla_gpu_enable_highest_priority_async_stream=true",
)


def _detect_platform() -> str:
    """Best-effort accelerator probe for when JAX_PLATFORMS is unset
    (jax auto-detect — the normal pod deployment): libtpu / TPU runtime
    env means 'tpu', visible CUDA devices mean 'gpu', else unknown."""
    import importlib.util
    try:
        if importlib.util.find_spec("libtpu") is not None:
            return "tpu"
    except (ImportError, ValueError):
        pass
    if any(os.environ.get(k) for k in
           ("TPU_WORKER_ID", "TPU_CHIPS_PER_HOST_BOUNDS",
            "PALLAS_AXON_POOL_IPS")):
        return "tpu"
    cuda = os.environ.get("CUDA_VISIBLE_DEVICES")
    if cuda not in (None, "", "-1"):
        return "gpu"
    # the common GPU deployment leaves CUDA_VISIBLE_DEVICES unset and
    # lets the jax plugin auto-detect — probe for the plugin/driver
    for mod in ("jax_cuda12_plugin", "jax_cuda11_plugin"):
        try:
            if importlib.util.find_spec(mod) is not None:
                return "gpu"
        except (ImportError, ValueError):
            pass
    if os.path.exists("/dev/nvidia0"):
        return "gpu"
    return ""


def ensure_xla_overlap_flags(platform: str = None, env: dict = None) -> bool:
    """Append the async-collective / latency-hiding-scheduler XLA flags
    to XLA_FLAGS when the overlap knob is on and the target platform is
    an accelerator.  Must take effect BEFORE a jax backend initializes
    (env flags are read once); returns True when the flags are (already)
    active, False when it was too late or the platform is host-only.

    platform defaults to the declared JAX_PLATFORMS (the dryrun/test
    environments pin 'cpu' there, which correctly skips these flags).
    env defaults to os.environ; pass a CHILD process's env dict (the
    launcher does) to arm a worker that has not started yet — the
    in-process too-late guard does not apply there."""
    if not overlap_enabled():
        return False
    # NB: when arming a child env dict, only ITS JAX_PLATFORMS counts —
    # _trainer_env builds children from a copy of os.environ, so a
    # parent setting is already there if it applies
    target = os.environ if env is None else env
    plat = (platform or target.get("JAX_PLATFORMS", "")).lower()
    if not plat:
        # JAX_PLATFORMS unset is the COMMON accelerator deployment (jax
        # auto-detects); probe the environment the way jax will
        plat = _detect_platform()
    if plat.startswith("cpu") or not plat:
        # unknown/host platform: adding accelerator-only flags would
        # abort backend startup
        return False
    flags = _TPU_FLAGS if "tpu" in plat else _GPU_FLAGS
    current = target.get("XLA_FLAGS", "")
    # exact flag-NAME comparison: substring matching would treat
    # `--xla_..._fusion` as present when only the longer
    # `--xla_..._fusion_fuse_all_gather` is set
    current_names = {f.split("=")[0] for f in current.split()}
    missing = [f for f in flags if f.split("=")[0] not in current_names]
    if not missing:
        return True
    if env is None and "jaxlib" in sys.modules:
        # backend plausibly initialized already: XLA_FLAGS edits would be
        # silently ignored — report honestly instead of pretending
        return False
    target["XLA_FLAGS"] = (current + " " + " ".join(missing)).strip()
    return True
