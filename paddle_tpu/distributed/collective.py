"""Collective communication API.

Reference: python/paddle/distributed/collective.py:101-457 (broadcast /
all_reduce / reduce / all_gather / scatter / barrier over c_* ops with
ring_id) and the C++ kernels operators/collective/ (SURVEY.md §2.3).

TPU-native semantics: there are two worlds —
1. COMPILED (the perf path): inside shard_map/pjit these functions lower
   to lax.psum / all_gather / ppermute / all_to_all over mesh axis names;
   XLA schedules them on ICI. Pass `axis_name=` (or rely on the ambient
   mesh axis 'dp').
2. EAGER single-process: world_size==1, every collective is the identity
   (matching the reference's behavior for nranks==1, collective.py:139).
   Multi-process eager collectives go through
   jax.experimental.multihost_utils when a multi-host runtime is
   initialized.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..core.autograd import apply
from ..core.tensor import Tensor
from . import env
from .mesh import get_mesh

__all__ = ["ReduceOp", "all_reduce", "all_gather", "reduce", "broadcast",
           "scatter", "barrier", "all_to_all", "send", "recv", "split",
           "new_group", "wait", "get_group"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """Communication group (reference collective.py Group w/ ring_id). On
    TPU a group is a mesh axis name (or None = world)."""

    def __init__(self, rank, nranks, id=0, axis_name=None, ranks=None):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.axis_name = axis_name
        self.ranks = ranks or list(range(nranks))

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"Group(rank={self.rank}, nranks={self.nranks}, " \
               f"axis={self.axis_name})"


_default_group = None
_groups = {}
_group_counter = 0


def get_group(group=None) -> Group:
    global _default_group
    if group is not None:
        return group
    if _default_group is None:
        _default_group = Group(env.get_rank(), env.get_world_size(), 0)
    return _default_group


def new_group(ranks=None, backend=None, axis_name=None) -> Group:
    global _group_counter
    _group_counter += 1
    world = env.get_world_size()
    ranks = ranks if ranks is not None else list(range(world))
    rank = env.get_rank()
    g = Group(ranks.index(rank) if rank in ranks else -1, len(ranks),
              _group_counter, axis_name=axis_name, ranks=ranks)
    _groups[_group_counter] = g
    return g


def _in_trace(x) -> bool:
    arr = x.data if isinstance(x, Tensor) else x
    return isinstance(arr, jax.core.Tracer)


_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               axis_name=None):
    """reference collective.py:157 all_reduce (c_allreduce_sum kernel,
    c_allreduce_op.h:54). Compiled: psum/pmax/pmin over the mesh axis."""
    g = get_group(group)
    name = axis_name or (g.axis_name if g else None)
    if _in_trace(tensor) and name is not None:
        if op == ReduceOp.AVG:
            return apply(lambda a: jax.lax.pmean(a, name), tensor,
                         name="all_reduce")
        red = _REDUCERS.get(op)
        if red is None:
            raise ValueError(f"unsupported reduce op {op} in traced mode")
        return apply(lambda a: red(a, name), tensor, name="all_reduce")
    if g.nranks <= 1:
        return tensor
    # multi-process eager: psum over processes via multihost utils
    from jax.experimental import multihost_utils
    arr = tensor.data if isinstance(tensor, Tensor) else tensor
    out = multihost_utils.process_allgather(arr)
    if op == ReduceOp.SUM:
        red = out.sum(axis=0)
    elif op == ReduceOp.MAX:
        red = out.max(axis=0)
    elif op == ReduceOp.MIN:
        red = out.min(axis=0)
    elif op == ReduceOp.AVG:
        red = out.mean(axis=0)
    else:
        red = out.prod(axis=0)
    if isinstance(tensor, Tensor):
        tensor._data = jnp.asarray(red)
        return tensor
    return red


def all_gather(tensor_list, tensor=None, group=None, sync_op=True,
               axis_name=None):
    """reference collective.py:313 all_gather (c_allgather). Two calling
    conventions: list-out eager parity, or functional (tensor only) which
    returns the gathered tensor (compiled path)."""
    if tensor is None:
        tensor = tensor_list
        tensor_list = None
    g = get_group(group)
    name = axis_name or (g.axis_name if g else None)
    if _in_trace(tensor) and name is not None:
        out = apply(lambda a: jax.lax.all_gather(a, name), tensor,
                    name="all_gather")
        return out
    if g.nranks <= 1:
        if tensor_list is not None:
            tensor_list.append(tensor)
            return tensor_list
        return tensor
    from jax.experimental import multihost_utils
    arr = tensor.data if isinstance(tensor, Tensor) else tensor
    gathered = multihost_utils.process_allgather(arr)
    if tensor_list is not None:
        for i in range(gathered.shape[0]):
            tensor_list.append(Tensor(jnp.asarray(gathered[i])))
        return tensor_list
    return Tensor(jnp.asarray(gathered))


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True,
           axis_name=None):
    """reference collective.py:231. On TPU SPMD there is no cheaper
    'reduce to one' than allreduce (ICI is all-to-all bandwidth), so this
    is allreduce; rank!=dst callers simply ignore the value."""
    return all_reduce(tensor, op=op, group=group, axis_name=axis_name)


def broadcast(tensor, src=0, group=None, sync_op=True, axis_name=None):
    """reference collective.py:101 (c_broadcast). Compiled: select the
    src slice and broadcast via all_gather/ppermute composition — XLA has
    no direct named-axis broadcast, psum of masked value is the idiom."""
    g = get_group(group)
    name = axis_name or (g.axis_name if g else None)
    if _in_trace(tensor) and name is not None:
        def fn(a):
            idx = jax.lax.axis_index(name)
            masked = jnp.where(idx == src, a, jnp.zeros_like(a))
            return jax.lax.psum(masked, name)
        return apply(fn, tensor, name="broadcast")
    if g.nranks <= 1:
        return tensor
    from jax.experimental import multihost_utils
    arr = tensor.data if isinstance(tensor, Tensor) else tensor
    out = multihost_utils.broadcast_one_to_all(arr)
    if isinstance(tensor, Tensor):
        tensor._data = jnp.asarray(out)
        return tensor
    return out


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True,
            axis_name=None):
    """reference collective.py:386 (c_scatter)."""
    g = get_group(group)
    name = axis_name or (g.axis_name if g else None)
    if _in_trace(tensor) and name is not None:
        # Compiled path ASSUMES the full input is replicated on every rank
        # (the common pjit case). The src rank's copy is selected with a
        # psum mask — matching c_scatter's "root provides the data"
        # semantics — then each rank dynamic-slices its own shard.
        def fn(a):
            idx = jax.lax.axis_index(name)
            mask = (idx == src).astype(a.dtype)
            from_src = jax.lax.psum(a * mask, name)
            shard = a.shape[0] // g.nranks
            return jax.lax.dynamic_slice_in_dim(
                from_src, idx * shard, shard, 0)
        return apply(fn, tensor, name="scatter")
    if g.nranks <= 1:
        if tensor_list:
            tensor._data = (tensor_list[src].data
                            if isinstance(tensor_list[src], Tensor)
                            else jnp.asarray(tensor_list[src]))
        return tensor
    raise NotImplementedError(
        "eager multi-process scatter: use broadcast + local slice")


def all_to_all(out_tensor_list, in_tensor_list=None, group=None,
               sync_op=True, axis_name=None):
    """All-to-all (ABSENT in the reference snapshot — SURVEY.md §2.5 marks
    expert parallelism as new design). Compiled: lax.all_to_all over the
    'ep' axis; this eager form handles world==1.

    Two calling conventions, mirroring all_gather:
    - functional (tensor only): stacked [n, ...] -> exchanged, the
      compiled fast path;
    - list API (out_tensor_list, in_tensor_list): reference parity.
      Inside a traced region (shard_map with the axis bound) the n input
      slices are stacked, exchanged with ONE lax.all_to_all, and
      unstacked into out_tensor_list — the path chunked MoE dispatch
      uses, and the one that was missing while all_reduce/all_gather
      already traced.
    """
    if in_tensor_list is None:
        # functional: single stacked tensor [n, ...] -> exchanged
        tensor = out_tensor_list
        g = get_group(group)
        name = axis_name or (g.axis_name if g else None)
        if _in_trace(tensor) and name is not None:
            return apply(lambda a: jax.lax.all_to_all(
                a, name, split_axis=0, concat_axis=0), tensor,
                name="all_to_all")
        return tensor
    g = get_group(group)
    name = axis_name or (g.axis_name if g else None)
    if name is not None and in_tensor_list \
            and any(_in_trace(t) for t in in_tensor_list):
        n = len(in_tensor_list)

        def fn(*xs):
            ex = jax.lax.all_to_all(jnp.stack(xs), name, split_axis=0,
                                    concat_axis=0)
            return tuple(ex[i] for i in range(n))

        outs = apply(fn, *in_tensor_list, name="all_to_all")
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        out_tensor_list.extend(outs)
        return out_tensor_list
    if g.nranks <= 1:
        out_tensor_list.extend(in_tensor_list)
        return out_tensor_list
    raise NotImplementedError("eager multi-process all_to_all")


def send(tensor, dst=0, group=None, sync_op=True):
    """p2p send (reference send_v2_op.cu.cc — pipeline boundary). In
    compiled pipelines this is a ppermute; eager single-process is a
    no-op paired with recv."""
    g = get_group(group)
    if g.nranks <= 1:
        _p2p_buffer.append(tensor)
        return tensor
    raise NotImplementedError("eager multi-process send: use pipeline mesh")


_p2p_buffer: List = []


def recv(tensor, src=0, group=None, sync_op=True):
    g = get_group(group)
    if g.nranks <= 1:
        if _p2p_buffer:
            val = _p2p_buffer.pop(0)
            tensor._data = val.data if isinstance(val, Tensor) else val
        return tensor
    raise NotImplementedError("eager multi-process recv: use pipeline mesh")


def barrier(group=None):
    """reference collective.py:457 (barrier op over gloo/nccl)."""
    g = get_group(group)
    if g.nranks <= 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor, group=None, use_calc_stream=True):
    """reference c_sync_calc_stream / c_sync_comm_stream ops — on TPU XLA
    owns scheduling; block_until_ready is the only user-visible sync."""
    arr = tensor.data if isinstance(tensor, Tensor) else tensor
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style tensor-parallel layer builder (reference
    collective.py:566 paddle.distributed.split: row/column parallel linear
    + sharded embedding). Returns the constructed parallel layer's output;
    prefer the explicit classes in
    paddle_tpu.distributed.parallel_layers."""
    from .parallel_layers import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(in_f, out_f,
                                         gather_output=gather_out,
                                         weight_attr=weight_attr,
                                         bias_attr=bias_attr)
        else:
            layer = RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                      bias_attr=bias_attr)
        return layer(x)
    if operation == "embedding":
        n_emb, dim = size
        layer = VocabParallelEmbedding(n_emb, dim, weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation}")
