"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

ABSENT in the reference snapshot (SURVEY.md §5: "no ring-attention /
Ulysses / context-parallel code — the TPU framework must design sequence
parallelism fresh, as a first-class parallel axis of the mesh"). The only
long-sequence tools the reference has are LoD variable-length batching and
recompute; this module adds the real thing.

Design (blockwise/ring attention, Liu et al. 2023, written for ICI):
- the sequence dimension of q/k/v is sharded over the 'sp' mesh axis;
  each device holds a contiguous block of T = S/sp positions;
- attention runs as sp rounds of blockwise softmax: every round each
  device attends its local queries against the K/V block it currently
  holds, then rotates the K/V block to its ring neighbor with
  ``lax.ppermute`` (XLA overlaps the ICI transfer with the next round's
  compute) while accumulating output in online-softmax form (running
  max m, normalizer l, unnormalized output o — the same recurrence the
  Pallas flash kernel uses within a chip);
- causal masking is positional: device r's queries at global positions
  r*T+i mask K/V positions by origin block, so late rounds on early
  ranks contribute nothing but keep the program SPMD-uniform;
- backward is jax.grad through the scan+ppermute (the transpose of a
  ppermute is the reverse-direction ppermute, so the ring runs backward
  in the backward pass automatically — no hand-written comm schedule).

Composes with 'dp' (batch dim) and 'tp' (heads) in one mesh: the
shard_map covers only the attention op; everything around it stays in
GSPMD-sharded pjit.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from ..core.autograd import apply
from ..core.tensor import Tensor
from . import mesh as _mesh
from .mesh import Mesh, PartitionSpec, get_mesh, shard_map
from .mesh import axis_size as _axis_size

__all__ = ["ring_attention", "ring_attention_local",
           "sequence_parallel_attention"]

# plain python float: a jnp scalar here would initialize the XLA
# backend at import time, breaking import-before-init_parallel_env
_NEG = -1e30


def ring_attention_local(q, k, v, axis_name: str = "sp",
                         causal: bool = True, scale: Optional[float] = None):
    """The per-device program (call inside shard_map with `axis_name`
    bound). q: local shard [B, T, H, D] where T = S/sp; k/v may carry
    fewer heads [B, T, Hkv, D] (GQA) — the UN-expanded blocks are what
    rotate, so grouped-query models pay Hkv/H of the MHA ring traffic.
    Returns the local output shard [B, T, H, D]."""
    sp = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, t, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    g = h // hkv                               # q heads per kv head
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B,T,H,D] -> [B,Hkv,G,T,D]; kv head j serves q heads [j*g,(j+1)*g)
    # inputs stay in their storage dtype (bf16) for the MXU einsums —
    # f32 matmul inputs run at a fraction of the bf16 rate; softmax
    # statistics accumulate in f32 via preferred_element_type
    qf = jnp.swapaxes(q, 1, 2).reshape(b, hkv, g, t, d)
    q_pos = rank * t + jnp.arange(t)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def block(o, m, l, k_cur, v_cur, i):
        src = (rank - i) % sp                  # origin block of k_cur
        kf = jnp.swapaxes(k_cur, 1, 2)                      # [B,Hkv,T,D]
        vf = jnp.swapaxes(v_cur, 1, 2)
        s = jnp.einsum("bhgqd,bhkd->bhgqk", qf, kf,
                       preferred_element_type=jnp.float32) * sc
        if causal:
            k_pos = src * t + jnp.arange(t)
            mask = q_pos[:, None] >= k_pos[None, :]        # [T,T]
            s = jnp.where(mask[None, None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))             # [B,Hkv,G,T]
        p = jnp.exp(s - m_new[..., None])
        if causal:
            # rows that are fully masked would otherwise exp(NEG-NEG)=1
            p = p * mask[None, None, None]
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhgqk,bhkd->bhgqd", p.astype(vf.dtype), vf,
            preferred_element_type=jnp.float32)
        return o, m_new, l

    def round_(carry, i):
        o, m, l, k_cur, v_cur = carry
        # compute reads k_cur, the permute also reads k_cur: XLA overlaps
        # the neighbor exchange with this round's matmuls
        o, m, l = block(o, m, l, k_cur, v_cur, i)
        k_cur = _mesh.ppermute(k_cur, axis_name, perm)
        v_cur = _mesh.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_cur, v_cur), None

    o0 = jnp.zeros((b, hkv, g, t, d), jnp.float32)
    m0 = jnp.full((b, hkv, g, t), _NEG, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, t), jnp.float32)
    carry = (o0, m0, l0, k, v)
    if sp > 1:
        # sp-1 rotated rounds in the scan; the final round runs outside
        # so the last (discarded) rotation is never issued
        carry, _ = jax.lax.scan(round_, carry, jnp.arange(sp - 1))
    o, m, l, k_last, v_last = carry
    o, m, l = block(o, m, l, k_last, v_last, sp - 1)
    out = o / jnp.maximum(l, 1e-30)[..., None]             # [B,Hkv,G,T,D]
    out = out.reshape(b, h, t, d)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)         # [B,T,H,D]


def ring_attention(q, k, v, mesh: Optional[Mesh] = None,
                   sp_axis: str = "sp", batch_axis: Optional[str] = "dp",
                   causal: bool = True, scale: Optional[float] = None):
    """Global-array entry point: shard the seq dim of q/k/v [B, S, H, D]
    over `sp_axis` and run the ring. Works inside a pjit/GSPMD trace (the
    compiled trainers) and eagerly on raw arrays; `mesh` defaults to the
    ambient mesh the trainer binds while tracing."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("ring_attention needs a mesh (pass mesh= or set "
                         "one with paddle_tpu.distributed.set_mesh)")
    ba = batch_axis if (batch_axis in mesh.axis_names and
                        mesh.shape[batch_axis] > 1) else None
    sp = mesh.shape[sp_axis] if sp_axis in mesh.axis_names else 1
    if sp <= 1:
        # no real sp axis: ring degenerates to plain attention (GQA k/v
        # expanded here; the composite needs full heads)
        from ..nn.functional.attention import _sdpa_reference
        h, hkv = q.shape[2], k.shape[2]
        if h != hkv:
            k = jnp.repeat(k, h // hkv, axis=2)
            v = jnp.repeat(v, h // hkv, axis=2)
        return _sdpa_reference(q, k, v, is_causal=causal, scale=scale)
    if q.shape[1] % max(sp, 1):
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by {sp_axis}="
            f"{sp}; pad the sequence or drop to dense attention")
    if ba is not None and q.shape[0] % mesh.shape[ba]:
        raise ValueError(
            f"batch {q.shape[0]} not divisible by {ba}="
            f"{mesh.shape[ba]}; use batch_axis=None or pad the batch")
    spec = PartitionSpec(ba, sp_axis, None, None)
    fn = partial(ring_attention_local, axis_name=sp_axis, causal=causal,
                 scale=scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def sequence_parallel_attention(query, key, value, mesh=None,
                                sp_axis: str = "sp", batch_axis="dp",
                                causal: bool = True, scale=None):
    """Tensor-level API (autograd-recorded): drop-in replacement for
    scaled_dot_product_attention when the sequence dim is sharded over
    'sp'. Inputs [B, S, H, D] with equal q/k/v sequence lengths."""
    m = mesh or get_mesh()

    def fn(q, k, v):
        return ring_attention(q, k, v, mesh=m, sp_axis=sp_axis,
                              batch_axis=batch_axis, causal=causal,
                              scale=scale)

    return apply(fn, query, key, value, name="ring_attention")
