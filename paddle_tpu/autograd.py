"""paddle.autograd — autodiff entry points on the eager tape.

Reference: the imperative engine surface (backward:
/root/reference/paddle/fluid/imperative/basic_engine.cc, partial grad:
partial_grad_engine.cc) exposed in Python as paddle.autograd. Here the
tape lives in core.autograd; this module is the stable public namespace.
"""
from .core.autograd import (  # noqa: F401
    backward,
    grad,
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
)

__all__ = ["backward", "grad", "no_grad", "enable_grad",
           "set_grad_enabled", "is_grad_enabled"]
