"""paddle.autograd — autodiff entry points on the eager tape.

Reference: the imperative engine surface (backward:
/root/reference/paddle/fluid/imperative/basic_engine.cc, partial grad:
partial_grad_engine.cc) exposed in Python as paddle.autograd. Here the
tape lives in core.autograd; this module is the stable public namespace.
"""
from .core.autograd import (  # noqa: F401
    backward,
    grad,
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
)

__all__ = ["backward", "grad", "no_grad", "enable_grad",
           "set_grad_enabled", "is_grad_enabled", "PyLayer",
           "PyLayerContext"]


class PyLayerContext:
    """Context passed through PyLayer.forward/backward (reference
    python/paddle/autograd PyLayerContext): carries saved tensors and
    arbitrary user attributes between the passes."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """User-defined eager op with a custom backward (reference
    paddle.autograd.PyLayer):

        class Exp(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = paddle.exp(x)
                ctx.save_for_backward(y)
                return y
            @staticmethod
            def backward(ctx, dy):
                (y,) = ctx.saved_tensor()
                return dy * y

    forward runs under no_grad (the custom backward REPLACES autodiff
    for this region, like the reference's PyLayer op); backward receives
    one cotangent per forward output and returns one gradient (or None)
    per differentiable forward input.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from .core.autograd import GradNode, _grad_enabled, no_grad
        from .core.tensor import Tensor

        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        out_arrs = tuple(o.data if isinstance(o, Tensor) else o
                         for o in outs)

        tensor_inputs = [a if isinstance(a, Tensor) else None
                         for a in args]
        needs = _grad_enabled() and any(
            t is not None and not t.stop_gradient for t in tensor_inputs)
        if not needs:
            wrapped = tuple(Tensor(a, stop_gradient=True)
                            for a in out_arrs)
            return wrapped if multi else wrapped[0]

        def vjp_fn(cots):
            cot_arrs = cots if isinstance(cots, tuple) else (cots,)
            cot_ts = tuple(Tensor(c, stop_gradient=True)
                           for c in cot_arrs)
            with no_grad():
                gs = cls.backward(ctx, *cot_ts)
            gs = gs if isinstance(gs, (tuple, list)) else (gs,)
            if len(gs) != len(args):
                # paddle allows returning grads only for tensor inputs
                it = iter(gs)
                gs = [next(it) if t is not None else None
                      for t in tensor_inputs]
            import numpy as np

            import jax

            def to_cot(t, g):
                if g is None:
                    # None = "no gradient" — hand the engine a float0 so
                    # it skips this input (its _is_float0 convention)
                    shape = tuple(t.data.shape) if t is not None else ()
                    return np.zeros(shape, jax.dtypes.float0)
                return g.data if isinstance(g, Tensor) else g

            return tuple(to_cot(t, g)
                         for t, g in zip(tensor_inputs, gs))

        node = GradNode(
            vjp_fn, tensor_inputs,
            [(tuple(a.shape), a.dtype) for a in out_arrs],
            name=cls.__name__, multi=multi, fn=None,
            raw_args=tuple(a.data if isinstance(a, Tensor) else a
                           for a in args))
        wrapped = tuple(
            Tensor(a, stop_gradient=False, _creator=(node, i))
            for i, a in enumerate(out_arrs))
        return wrapped if multi else wrapped[0]
