"""paddle.linalg namespace (reference python/paddle/linalg.py — re-export
of tensor/linalg.py, which holds the XLA lowerings)."""
from .tensor.linalg import (  # noqa: F401
    matmul, bmm, dot, mv, norm, p_norm, dist, cholesky, inv, matrix_power,
    multi_dot, det, slogdet, svd, qr, eig, eigh, eigvals, eigvalsh,
    matrix_rank, pinv, solve, triangular_solve, lstsq, cond, lu,
    cholesky_solve, cross, householder_product, corrcoef, cov)
from .tensor.math import histogram  # noqa: F401
