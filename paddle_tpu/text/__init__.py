"""paddle.text parity + the variable-length-sequence utilities the core
doctrine points at.

Reference: /root/reference/python/paddle/text/ (datasets: Conll05st, Imdb,
Imikolov, Movielens, UCIHousing, WMT14, WMT16). The reference handles
variable-length data with LoDTensor (lod_tensor.h:114); TPU/XLA wants
static shapes, so this module provides the dense-padding + mask
equivalents (`pad_sequences`, `sequence_mask`) that every model here uses
instead of LoD.
"""
from .utils import (  # noqa: F401
    sequence_mask, pad_sequences, truncate_sequences, shift_tokens_right,
    causal_mask, padding_attn_mask)
from .datasets import (  # noqa: F401
    UCIHousing, Imdb, Imikolov, Movielens, WMT14, Conll05st, WMT16)
from .decoding import (  # noqa: F401
    beam_search, greedy_search, gather_tree, gpt_step_fn,
    viterbi_decode)

__all__ = [
    "sequence_mask", "pad_sequences", "truncate_sequences",
    "shift_tokens_right", "causal_mask", "padding_attn_mask",
    "UCIHousing", "Imdb", "Imikolov", "Movielens", "WMT14", "WMT16",
    "Conll05st", "beam_search", "greedy_search", "gather_tree",
    "gpt_step_fn", "viterbi_decode",
]
