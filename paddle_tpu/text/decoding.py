"""Sequence decoding: beam search, greedy, sampling + gather_tree.

Reference: /root/reference/paddle/fluid/operators/beam_search_op.h
(per-step top-k over K*V candidates with parent pointers),
beam_search_decode_op (backtracking), gather_tree_op.cc, and the Python
orchestration in fluid/layers/rnn.py (BeamSearchDecoder +
dynamic_decode).

TPU-native shape: the whole decode is ONE `lax.while_loop` over time —
the per-step top-k, parent gather, and finished masking are fixed-shape
jnp ops writing into preallocated [max_len, ...] buffers, so the entire
loop compiles to a single XLA while-program (the reference re-enters
the executor per step) AND exits early: once every batch row / beam has
emitted EOS the loop stops instead of burning the remaining max_len
steps (the buffers are EOS/identity-filled, so outputs are identical to
the full-length run).  States carry a leading [B*K] dim;
`step_fn(tokens, state) -> (logits, state)` is any jax function.

`gpt_step_fn` adapts a models.GPTForCausalLM + its StaticKVCache to
that contract (the cache's [layers, N, ...] leaves are re-gathered on
axis 1 by the beam parent shuffle), which is what wires these decoders
to the real transformer decode step.
"""
from __future__ import annotations

from typing import Callable, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap as _arr

__all__ = ["beam_search", "greedy_search", "gather_tree",
           "viterbi_decode", "gpt_step_fn"]

_NEG = -1e9


def gpt_step_fn(model) -> Callable:
    """step_fn over a GPTForCausalLM: ``step(tokens [N], cache) ->
    (logits [N, V], cache)`` where cache is a models.StaticKVCache with
    N slots (``model.init_kv_cache(N)``, optionally pre-filled with a
    prompt per slot via ``model.prefill``).  Every step appends one
    token per slot — recompile-free by construction.  Call
    ``model.eval()`` first so dropout layers are inert."""
    def step(tokens, cache):
        active = jnp.ones((cache.batch_slots,), jnp.int32)
        logits, cache = model.decode_step(tokens, cache, active)
        return logits, cache
    return step




def gather_tree(token_ids, parent_ids):
    """Backtrack beam parent pointers into full sequences
    (gather_tree_op.cc). token_ids/parent_ids: [T, B, K] -> [T, B, K]
    where output[:, b, k] is the COMPLETE sequence feeding beam k at the
    final step."""
    ids = _arr(token_ids)
    parents = _arr(parent_ids)
    T = ids.shape[0]

    def back(carry, t):
        beam = carry                               # [B, K] current beam
        tok = jnp.take_along_axis(ids[t], beam, axis=1)
        par = jnp.take_along_axis(parents[t], beam, axis=1)
        return par, tok

    k0 = jnp.broadcast_to(jnp.arange(ids.shape[2])[None, :],
                          ids.shape[1:])
    _, toks = jax.lax.scan(back, k0, jnp.arange(T - 1, -1, -1))
    return Tensor(toks[::-1])


def beam_search(step_fn: Callable, init_state, batch_size: int,
                beam_size: int, max_len: int, bos_id: int, eos_id: int,
                length_penalty: float = 0.0) -> Tuple[Tensor, Tensor]:
    """Compiled beam search. Returns (sequences [B, K, max_len],
    scores [B, K]) sorted best-first.

    step_fn(tokens [B*K], state) -> (logits [B*K, V], new_state); state
    leaves carry a leading B*K dim (tile your encoder state K times).
    length_penalty: GNMT alpha — scores divided by ((5+len)/6)^alpha.
    """
    B, K = batch_size, beam_size

    def expand_logp(logits):
        return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    def regather(a, parent):
        """Shuffle a state leaf by beam parents.  Leaves with a leading
        [B*K] dim gather on axis 0; [L, B*K, ...] leaves (a
        StaticKVCache's stacked-layer k/v) gather on axis 1.  (A leaf
        whose axis-0 length coincidentally equals B*K takes the axis-0
        branch — lay out such state batch-first.)"""
        if a.ndim >= 1 and a.shape[0] == B * K:
            r = a.reshape((B, K) + a.shape[1:])[
                jnp.arange(B)[:, None], parent]
            return r.reshape((B * K,) + a.shape[1:])
        if a.ndim >= 2 and a.shape[1] == B * K:
            r = a.reshape((a.shape[0], B, K) + a.shape[2:])[
                :, jnp.arange(B)[:, None], parent]
            return r.reshape((a.shape[0], B * K) + a.shape[2:])
        raise ValueError(
            f"beam_search state leaf {a.shape} carries no [B*K]={B * K} "
            f"dim on axis 0 or 1")

    def cond(carry):
        t, _, _, finished, _, _, _ = carry
        # EOS early-exit: the while-program stops the moment every beam
        # of every row has finished (the scan version always paid
        # max_len steps; the buffers are EOS/identity-initialized so
        # the output is bit-identical)
        return (t < max_len) & ~jnp.all(finished)

    def step(carry):
        t, tokens, cum, finished, state, toks_buf, par_buf = carry
        logits, state = step_fn(tokens.reshape(-1), state)
        V = logits.shape[-1]
        logp = expand_logp(logits).reshape(B, K, V)
        # finished beams emit ONLY eos at no cost (the reference keeps
        # them alive in the beam with frozen scores)
        eos_only = jnp.full((V,), _NEG).at[eos_id].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :],
                         logp)
        total = cum[..., None] + logp             # [B, K, V]
        flat = total.reshape(B, K * V)
        cum_new, idx = jax.lax.top_k(flat, K)     # [B, K]
        parent = idx // V
        token = idx % V
        finished = jnp.take_along_axis(finished, parent, axis=1) | \
            (token == eos_id)
        state = jax.tree_util.tree_map(lambda a: regather(a, parent),
                                       state)
        toks_buf = toks_buf.at[t].set(token)
        par_buf = par_buf.at[t].set(parent)
        return (t + 1, token, cum_new, finished, state, toks_buf,
                par_buf)

    tokens0 = jnp.full((B, K), bos_id, jnp.int32)
    # only beam 0 is live at t=0, or every beam would decode identically
    cum0 = jnp.tile(jnp.asarray([0.0] + [_NEG] * (K - 1),
                                jnp.float32)[None, :], (B, 1))
    fin0 = jnp.zeros((B, K), bool)
    # unexecuted steps: eos tokens with identity parents, so gather_tree
    # backtracks through them unchanged
    toks0 = jnp.full((max_len, B, K), eos_id, jnp.int32)
    par0 = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, None, :],
                            (max_len, B, K))
    _, tokens, cum, finished, _, toks, parents = jax.lax.while_loop(
        cond, step,
        (jnp.asarray(0, jnp.int32), tokens0, cum0, fin0, init_state,
         toks0, par0))

    seqs = gather_tree(toks, parents).data        # [T, B, K]
    seqs = jnp.moveaxis(seqs, 0, 2)               # [B, K, T]
    # length penalty at final ranking (fluid/layers/rnn.py
    # BeamSearchDecoder's GNMT score)
    lengths = jnp.minimum(
        jnp.argmax((seqs == eos_id).astype(jnp.int32), axis=2) + 1,
        max_len).astype(jnp.float32)
    has_eos = (seqs == eos_id).any(axis=2)
    lengths = jnp.where(has_eos, lengths, float(max_len))
    denom = ((5.0 + lengths) / 6.0) ** length_penalty
    scores = cum / denom
    order = jnp.argsort(-scores, axis=1)
    seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return Tensor(seqs), Tensor(scores)


def greedy_search(step_fn: Callable, init_state, batch_size: int,
                  max_len: int, bos_id: int, eos_id: int
                  ) -> Tensor:
    """Greedy argmax decode as one XLA while-program with EOS
    early-exit: the loop stops once every row has finished (the output
    buffer is EOS-filled, so results match the full-length run).
    Returns [B, max_len]."""
    B = batch_size

    def cond(carry):
        t, _, finished, _, _ = carry
        return (t < max_len) & ~jnp.all(finished)

    def step(carry):
        t, tokens, finished, state, out = carry
        logits, state = step_fn(tokens, state)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, eos_id, nxt)
        finished = finished | (nxt == eos_id)
        return t + 1, nxt, finished, state, out.at[t].set(nxt)

    tokens0 = jnp.full((B,), bos_id, jnp.int32)
    fin0 = jnp.zeros((B,), bool)
    out0 = jnp.full((max_len, B), eos_id, jnp.int32)
    _, _, _, _, toks = jax.lax.while_loop(
        cond, step,
        (jnp.asarray(0, jnp.int32), tokens0, fin0, init_state, out0))
    return Tensor(jnp.moveaxis(toks, 0, 1))


def viterbi_decode(potentials, transition, lengths=None,
                   include_bos_eos_tag=True):
    """CRF Viterbi decode (reference crf_decoding_op.h /
    paddle.text.viterbi_decode): emission potentials [B, T, N] +
    transition [N, N] -> (scores [B], best paths [B, T]).  One lax.scan
    forward pass keeping per-tag backpointers, one reverse scan to read
    the argmax path; rows past `lengths` freeze (mask convention).
    include_bos_eos_tag treats the last two tags as BOS/EOS like the
    reference (start/stop transition rows added at the boundaries)."""
    em = _arr(potentials).astype(jnp.float32)       # [B, T, N]
    tr = _arr(transition).astype(jnp.float32)       # [N, N]
    b, t, n = em.shape
    if lengths is None:
        ln = jnp.full((b,), t, jnp.int32)
    else:
        ln = _arr(lengths).astype(jnp.int32)

    if include_bos_eos_tag:
        # reference convention: tag N-2 = BOS, N-1 = EOS
        start = tr[n - 2]                           # [N]
        stop = tr[:, n - 1]                         # [N]
    else:
        start = jnp.zeros((n,), jnp.float32)
        stop = jnp.zeros((n,), jnp.float32)

    alpha0 = em[:, 0] + start[None, :]              # [B, N]

    def fwd(carry, i):
        alpha = carry                               # [B, N]
        # score of arriving at tag j from tag k
        cand = alpha[:, :, None] + tr[None, :, :]   # [B, from, to]
        best = cand.max(axis=1) + em[:, i]          # [B, N]
        bp = cand.argmax(axis=1).astype(jnp.int32)  # [B, N]
        keep = (i < ln)[:, None]
        alpha = jnp.where(keep, best, alpha)
        return alpha, bp

    alpha, bps = jax.lax.scan(fwd, alpha0, jnp.arange(1, t))
    # EOS transition applies at each row's LAST valid position
    final = alpha + stop[None, :]
    scores = final.max(axis=1)
    last_tag = final.argmax(axis=1).astype(jnp.int32)   # [B]

    def back(carry, i):
        tag = carry                                  # [B]
        # bps[i] maps position i+1's tag -> best previous tag
        prev = jnp.take_along_axis(bps[i], tag[:, None],
                                   axis=1)[:, 0]
        # positions at/after the row's end keep the frozen tag
        tag_new = jnp.where(i + 1 < ln, prev, tag)
        return tag_new, tag

    tag_final, tags_rev = jax.lax.scan(
        back, last_tag, jnp.arange(t - 2, -1, -1))
    path = jnp.concatenate(
        [tag_final[:, None],
         jnp.moveaxis(tags_rev[::-1], 0, 1)], axis=1)   # [B, T]
    return Tensor(scores), Tensor(path)
