"""Dense-padding / masking utilities — the TPU-native replacement for the
reference's LoD (level-of-detail) variable-length machinery
(/root/reference/paddle/fluid/framework/lod_tensor.h:62,114 and the
sequence_ops operator family). XLA requires static shapes; ragged batches
become [B, max_len] plus a mask, and every sequence op is a masked dense
op the compiler can fuse.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import apply

__all__ = ["sequence_mask", "pad_sequences", "truncate_sequences",
           "shift_tokens_right", "causal_mask", "padding_attn_mask"]


def sequence_mask(lengths, maxlen: Optional[int] = None, dtype="bool"):
    """[B] lengths -> [B, maxlen] mask (reference
    fluid/layers/sequence_lod.py sequence_mask / sequence_mask_op)."""
    arr = lengths.data if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(np.asarray(arr).max())

    def fn(l):
        pos = jnp.arange(maxlen, dtype=jnp.int32)
        return (pos[None, :] < l[..., None].astype(jnp.int32)).astype(dtype)

    return apply(fn, Tensor(arr), name="sequence_mask")


def pad_sequences(seqs: Sequence[Sequence[int]], maxlen: Optional[int] = None,
                  pad_value=0, dtype=np.int64, truncate_from="right",
                  return_lengths=False):
    """Ragged python sequences -> dense [B, maxlen] numpy array (+ lengths).
    This is where LoD data enters the static-shape world."""
    if maxlen is None:
        maxlen = max((len(s) for s in seqs), default=0)
    out = np.full((len(seqs), maxlen), pad_value, dtype=dtype)
    lengths = np.zeros((len(seqs),), np.int64)
    for i, s in enumerate(seqs):
        s = list(s)
        if len(s) > maxlen:
            s = s[-maxlen:] if truncate_from == "left" else s[:maxlen]
        out[i, :len(s)] = s
        lengths[i] = len(s)
    if return_lengths:
        return out, lengths
    return out


def truncate_sequences(seqs, maxlen: int, truncate_from="right"):
    return [list(s)[-maxlen:] if truncate_from == "left" else
            list(s)[:maxlen] for s in seqs]


def shift_tokens_right(input_ids, pad_id: int = 0):
    """Labels for causal LM: labels[t] = input[t+1], last position padded."""
    arr = input_ids.data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)

    def fn(a):
        return jnp.concatenate(
            [a[:, 1:], jnp.full((a.shape[0], 1), pad_id, a.dtype)], axis=1)

    return apply(fn, Tensor(arr), name="shift_tokens_right")


def causal_mask(seq_len: int, dtype="bool"):
    """[1, 1, S, S] lower-triangular mask for decoder attention."""
    m = jnp.tril(jnp.ones((seq_len, seq_len), bool))
    return Tensor(m[None, None].astype(dtype))


def padding_attn_mask(lengths, seq_len: int):
    """[B] lengths -> [B, 1, 1, S] boolean key-padding mask usable as
    attn_mask in scaled_dot_product_attention (broadcasts over heads and
    query positions)."""
    m = sequence_mask(lengths, maxlen=seq_len, dtype="bool")
    arr = m.data
    return Tensor(arr[:, None, None, :])
