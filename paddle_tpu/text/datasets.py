"""Text datasets (reference /root/reference/python/paddle/text/datasets/:
uci_housing.py, imdb.py, imikolov.py, movielens.py, wmt14.py, wmt16.py,
conll05.py).

The reference downloads archives from paddle's CDN at construction time;
this environment has no egress, so every dataset here takes a
`data_file` pointing at a local copy with the SAME on-disk format the
reference expects, and additionally supports `mode='synthetic'` which
generates a deterministic in-memory sample set with the right shapes —
enough for tests, examples, and benchmarks to run hermetically.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile
from typing import List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "WMT14",
           "WMT16", "Conll05st"]


def _need_file(data_file, name):
    if data_file is None:
        raise ValueError(
            f"{name}: pass data_file=<local path> (no network downloads "
            f"in this runtime) or mode='synthetic' for generated data")
    if not os.path.exists(data_file):
        raise FileNotFoundError(f"{name}: data_file {data_file} not found")
    return data_file


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py:34): 13
    features -> price, features normalized exactly like the reference
    (per-column max/min/avg over the train split)."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train"):
        self.mode = mode.lower()
        if self.mode == "synthetic" or data_file is None:
            rng = np.random.RandomState(42)
            n = 404 if self.mode != "test" else 102
            self.data = rng.randn(n, self.FEATURE_DIM).astype(np.float32)
            w = rng.randn(self.FEATURE_DIM).astype(np.float32)
            self.label = (self.data @ w + 0.1 * rng.randn(n)).astype(
                np.float32)[:, None]
            return
        path = _need_file(data_file, "UCIHousing")
        raw = np.fromfile(path, sep=" ", dtype=np.float32)
        raw = raw.reshape(-1, self.FEATURE_DIM + 1)
        maximums = raw.max(axis=0)
        minimums = raw.min(axis=0)
        avgs = raw.sum(axis=0) / raw.shape[0]
        for i in range(self.FEATURE_DIM):
            raw[:, i] = (raw[:, i] - avgs[i]) / (maximums[i] - minimums[i])
        split = int(raw.shape[0] * 0.8)
        part = raw[:split] if self.mode == "train" else raw[split:]
        self.data = part[:, :-1]
        self.label = part[:, -1:]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py): tokenized reviews -> 0/1."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 seq_len=64, vocab_size=5000):
        self.mode = mode.lower()
        self.seq_len = seq_len
        if self.mode == "synthetic" or data_file is None:
            rng = np.random.RandomState(7)
            n = 256
            self.docs = rng.randint(2, vocab_size, (n, seq_len)).astype(
                np.int64)
            self.labels = rng.randint(0, 2, (n,)).astype(np.int64)
            self.word_idx = {f"w{i}": i for i in range(vocab_size)}
            return
        path = _need_file(data_file, "Imdb")
        pat = re.compile(
            rf"aclImdb/{'train' if self.mode == 'train' else 'test'}"
            rf"/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if pat.match(m.name):
                    toks = tf.extractfile(m).read().decode(
                        "latin-1").lower().split()
                    docs.append(toks)
                    labels.append(0 if "/neg/" in m.name else 1)
                    for t in toks:
                        freq[t] = freq.get(t, 0) + 1
        words = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i + 2 for i, w in enumerate(words)}
        from .utils import pad_sequences
        ids = [[self.word_idx.get(t, 1) for t in d] for d in docs]
        self.docs = pad_sequences(ids, maxlen=seq_len)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram LM dataset (reference imikolov.py): sliding n-grams."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, vocab_size=2000):
        self.window = window_size
        if mode.lower() == "synthetic" or data_file is None:
            rng = np.random.RandomState(11)
            stream = rng.randint(2, vocab_size, (20000,)).astype(np.int64)
            self.samples = np.lib.stride_tricks.sliding_window_view(
                stream, window_size).copy()
            self.word_idx = {f"w{i}": i for i in range(vocab_size)}
            return
        path = _need_file(data_file, "Imikolov")
        fname = f"./simple-examples/data/ptb.{mode}.txt"
        freq = {}
        lines = []
        with tarfile.open(path) as tf:
            for line in tf.extractfile(fname).read().decode().split("\n"):
                toks = ["<s>"] + line.strip().split() + ["<e>"]
                lines.append(toks)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        words = [w for w, c in freq.items() if c >= min_word_freq and
                 w != "<unk>"]
        words.sort(key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        unk = len(self.word_idx)
        self.word_idx["<unk>"] = unk
        samples = []
        for toks in lines:
            ids = [self.word_idx.get(t, unk) for t in toks]
            for i in range(len(ids) - window_size + 1):
                samples.append(ids[i:i + window_size])
        self.samples = np.asarray(samples, np.int64)

    def __getitem__(self, idx):
        row = self.samples[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens-1M rating prediction (reference movielens.py)."""

    def __init__(self, data_file=None, mode="train"):
        if mode.lower() == "synthetic" or data_file is None:
            rng = np.random.RandomState(5)
            n = 512
            self.rows = [
                (rng.randint(1, 6041), rng.randint(0, 2), rng.randint(1, 57),
                 rng.randint(0, 21), rng.randint(1, 3953),
                 rng.randint(0, 19, size=(3,)).astype(np.int64),
                 np.float32(rng.randint(1, 6)))
                for _ in range(n)]
            return
        raise NotImplementedError(
            "Movielens from archive: supply mode='synthetic' or implement "
            "loading from a local ml-1m archive")

    def __getitem__(self, idx):
        u, gender, age, job, mov, cats, rating = self.rows[idx]
        return (np.int64(u), np.int64(gender), np.int64(age),
                np.int64(job), np.int64(mov), cats, rating)

    def __len__(self):
        return len(self.rows)


class _ParallelCorpus(Dataset):
    """Shared shape for WMT14/WMT16: (src_ids, trg_ids, trg_next)."""

    def __init__(self, mode, seq_len, vocab_size, seed):
        rng = np.random.RandomState(seed)
        n = 256
        self.src = rng.randint(3, vocab_size, (n, seq_len)).astype(np.int64)
        self.trg = rng.randint(3, vocab_size, (n, seq_len)).astype(np.int64)
        self.trg[:, 0] = 0  # <s>
        self.trg_next = np.roll(self.trg, -1, axis=1)
        self.trg_next[:, -1] = 1  # <e>

    def __getitem__(self, idx):
        return self.src[idx], self.trg[idx], self.trg_next[idx]

    def __len__(self):
        return len(self.src)


class WMT14(_ParallelCorpus):
    """reference wmt14.py; synthetic-only here (see module docstring)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 seq_len=32):
        if data_file is not None:
            raise NotImplementedError(
                "WMT14 archive loading needs network-fetched dicts; use "
                "mode='synthetic'")
        super().__init__(mode, seq_len, min(dict_size, 30000), seed=14)


class WMT16(_ParallelCorpus):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, seq_len=32):
        if data_file is not None:
            raise NotImplementedError(
                "WMT16 archive loading: use mode='synthetic'")
        super().__init__(mode, seq_len, min(src_dict_size, 30000), seed=16)


class Conll05st(Dataset):
    """SRL dataset (reference conll05.py); synthetic-only: returns the
    same 9-column tuple layout."""

    def __init__(self, data_file=None, mode="train", seq_len=32,
                 word_dict_size=5000, label_dict_size=59):
        rng = np.random.RandomState(55)
        n = 128
        self.cols = [
            tuple(rng.randint(0, word_dict_size, (seq_len,)).astype(np.int64)
                  for _ in range(8)) +
            (rng.randint(0, label_dict_size, (seq_len,)).astype(np.int64),)
            for _ in range(n)]

    def __getitem__(self, idx):
        return self.cols[idx]

    def __len__(self):
        return len(self.cols)
