"""Weight-decay regularizers (reference python/paddle/fluid/regularizer.py:
L1DecayRegularizer / L2DecayRegularizer — there they append decay ops onto
the gradient; here `apply(param, grad)` returns the decayed gradient
array, fused by XLA into the optimizer update)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def apply(self, p, g):
        raise NotImplementedError

    @property
    def coeff(self):
        return self._coeff


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def apply(self, p, g):
        return g + self._coeff * jnp.sign(p)

    def __repr__(self):
        return f"L1Decay({self._coeff})"


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def apply(self, p, g):
        return g + self._coeff * p

    def __repr__(self):
        return f"L2Decay({self._coeff})"
